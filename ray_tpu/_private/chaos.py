"""Fault-injection harnesses: process kills and link-level degradation.

rpc.py's ``_Chaos`` drops *messages*; ``ProcessChaos`` kills *processes*
— SIGKILL, no warning — because the crash paths (a SIGKILL'd worker,
agent, or GCS) are what dominate production failures on preemptible TPU
fleets, and message-level drops never exercise them.  ``LinkChaos``
degrades the *byte stream* itself (delay, jitter, bandwidth throttle,
asymmetric blackhole) — the GRAY failures (Huang et al., HotOS'17) that
neither drops nor kills reproduce: the peer is alive, the TCP session is
up, and everything is merely late or one-directional.  The ProcessChaos
spec mirrors the ``rpc_chaos`` style (config ``process_chaos``):

    'class=N:period_s[:delay_s],...'

      class     worker | agent | gcs
      N         total kills of that class
      period_s  seconds between kills (default 5; also the default
                first delay)
      delay_s   seconds before the first kill (optional)

e.g. ``'worker=3:2:1,gcs=1:10'`` — SIGKILL one worker at t≈1, 3, 5 and
the GCS at t≈10.  Schedules are deterministic; victim choice among the
live candidates uses a fixed-seed RNG.

Victims are discovered by scanning ``/proc`` for processes whose stderr
(fd 2) points into this session's log directory — worker/agent/GCS
daemons all log to ``<session_dir>/logs/<class>…err``.  fd-based
discovery also finds zygote-FORKED workers, whose ``/proc`` cmdline and
environ still show the zygote's (a fork without exec keeps both).

An ``agent`` kill takes the whole node down the way a preemption does:
the agent plus its zygote and workers, found via the ppid chain.  A
killed GCS can be respawned through a ``restart`` callback (same port,
same journal) so the cluster exercises journal-replay recovery — see
``cluster_utils.Cluster.restart_gcs``.  When a warm standby is armed
(``Cluster(gcs_standby=True)``), the ``gcs`` class still targets only
the PRIMARY — the standby logs to ``gcs_standby.err``, which the
``gcs.`` basename prefix deliberately excludes — and the restart
callback waits for the standby's epoch-fenced promotion instead of
respawning (``Cluster._gcs_failover_restart``).
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
from typing import Callable, Dict, Iterable, Optional

logger = logging.getLogger("ray_tpu.chaos")

CLASSES = ("worker", "agent", "gcs")

# ---------------------------------------------------------------------------
# Link chaos (config `link_chaos`): gray-failure injection on the RPC
# byte stream.  Applied inside each enabled PROCESS by rpc.Connection —
# enabling it on one node's daemons (per-node _system_config) is
# slow-node mode; a `match` filter narrows a rule to specific links.
#
#   spec:  'rule[,rule...]'
#   rule:  '[match/]kind=f1[:f2[:f3[:f4]]]'
#
#   kind        fields                          meaning
#   out_delay   delay_s[:jitter_s[:after_s[:dur_s]]]   delay outbound bytes
#   in_delay    delay_s[:jitter_s[:after_s[:dur_s]]]   delay inbound bytes
#   out_bw      bytes_per_s[:after_s[:dur_s]]          throttle outbound
#   in_bw       bytes_per_s[:after_s[:dur_s]]          throttle inbound
#   out_drop    [after_s[:dur_s]]                      blackhole outbound
#   in_drop     [after_s[:dur_s]]                      blackhole inbound
#
# `match` is a substring filter against the link descriptor
# "<conn name>|<peer host:port>" (e.g. 'agent->agent/' or
# ':45123/out_delay=...'); no match = every link of the process.
# out_drop alone is an ASYMMETRIC partition: A's requests (and replies)
# never reach B while B->A bytes still flow — the TCP session stays up,
# which is exactly what makes gray partitions invisible to crash
# detectors.  after_s/dur_s give deterministic schedules (netsplit that
# heals, delay that starts late) measured from injector creation.
# Jitter uses a seeded RNG like the other injectors.
# ---------------------------------------------------------------------------

LINK_KINDS = ("out_delay", "in_delay", "out_bw", "in_bw",
              "out_drop", "in_drop")


def parse_link_spec(spec: str):
    """'[match/]kind=f1[:f2...]' rules -> list of rule dicts."""
    rules = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        # '=' and fields are optional: a bare 'out_drop' is a valid
        # full asymmetric partition from t=0 (all fields default).
        lhs, _, rhs = part.partition("=")
        match = ""
        if "/" in lhs:
            match, lhs = lhs.rsplit("/", 1)
        if lhs not in LINK_KINDS:
            raise ValueError(
                f"unknown link_chaos kind {lhs!r} (expected one of "
                f"{LINK_KINDS})")
        f = [float(x) for x in rhs.split(":")] if rhs else []
        rule = {"kind": lhs, "match": match, "after": 0.0, "dur": None}
        if lhs.endswith("_delay"):
            rule["delay"] = f[0] if f else 0.0
            rule["jitter"] = f[1] if len(f) > 1 else 0.0
            rule["after"] = f[2] if len(f) > 2 else 0.0
            rule["dur"] = f[3] if len(f) > 3 else None
        elif lhs.endswith("_bw"):
            if not f or f[0] <= 0:
                raise ValueError("link_chaos bw needs bytes_per_s > 0")
            rule["bw"] = f[0]
            rule["after"] = f[1] if len(f) > 1 else 0.0
            rule["dur"] = f[2] if len(f) > 2 else None
            rule["next_free"] = 0.0      # token-bucket state (monotonic)
        else:                            # *_drop
            rule["after"] = f[0] if f else 0.0
            rule["dur"] = f[1] if len(f) > 1 else None
        rules.append(rule)
    return rules


class LinkChaos:
    """Deterministic link-degradation planner, consulted by
    rpc.Connection for every chunk of bytes it moves.

        lc = LinkChaos("out_delay=0.5,agent->agent/in_drop=")
        drop, delay_s = lc.plan("out", "agent|127.0.0.1:4567", nbytes)

    The planner itself is sync and transport-agnostic; the async side
    (ordered delayed delivery) lives in rpc.py.  Schedules (`after`/
    `dur`) are measured from construction; jitter is drawn from a
    seeded RNG so runs replay identically."""

    def __init__(self, spec: str, seed: int = 0xC0FFEE):
        self.rules = parse_link_spec(spec)
        self._rng = random.Random(seed)
        self._t0 = time.monotonic()

    def _active(self, rule, now: float) -> bool:
        t = now - self._t0
        if t < rule["after"]:
            return False
        return rule["dur"] is None or t < rule["after"] + rule["dur"]

    def matches_in(self, desc: str) -> bool:
        """Any inbound rule that could EVER apply to this link (schedule
        windows ignored — a rule can activate later).  rpc.Connection
        disables the native recv-into-arena takeover on such links:
        delayed/dropped inbound delivery requires buffering the bytes,
        which is exactly what the takeover bypasses."""
        for rule in self.rules:
            if rule["kind"].startswith("in_") and \
                    (not rule["match"] or rule["match"] in desc):
                return True
        return False

    def plan(self, direction: str, desc: str, nbytes: int):
        """(drop, delay_s) for `nbytes` moving `direction` ('out'|'in')
        on the link described by `desc`."""
        drop = False
        delay = 0.0
        now = time.monotonic()
        prefix = direction + "_"
        for rule in self.rules:
            if not rule["kind"].startswith(prefix):
                continue
            if rule["match"] and rule["match"] not in desc:
                continue
            if not self._active(rule, now):
                continue
            kind = rule["kind"]
            if kind.endswith("_drop"):
                drop = True
            elif kind.endswith("_delay"):
                d = rule["delay"]
                if rule["jitter"]:
                    d += self._rng.uniform(-rule["jitter"], rule["jitter"])
                delay += max(0.0, d)
            else:                        # bandwidth token bucket
                start = max(now, rule["next_free"])
                rule["next_free"] = start + nbytes / rule["bw"]
                delay += start - now
        return drop, delay

# ---------------------------------------------------------------------------
# Memory chaos (config `mem_chaos`): shrink the EFFECTIVE memory budget
# under load, then restore it.  Unlike process/link chaos this injects no
# failures directly — it squeezes the policy layer (arena admission/spill
# thresholds, the KV page pool) so the tiered-memory machinery (eviction
# ordering, create-queue backpressure, KV demotion) runs for real while
# the workload is live.  Square-wave schedule: within each period the
# first half runs at full budget, the second half at the squeezed
# fraction — deterministic, so soak assertions can count squeeze windows.
#
#   spec: 'arena=frac:period_s[,pool=frac]'
#
#     arena frac   effective arena budget during a squeeze window, as a
#                  fraction of real capacity (0 < frac <= 1)
#     period_s     squeeze cycle length (half squeezed, half restored)
#     pool frac    optional: KV page-pool fraction during the window
#
# e.g. 'arena=0.25:4,pool=0.5' — every 4s the arena policy budget drops
# to 25% (driving spill/eviction/backpressure) and the engine parks half
# its free KV pages, for 2s, then both restore.
# ---------------------------------------------------------------------------


def parse_mem_spec(spec: str) -> dict:
    """'arena=frac:period_s[,pool=frac]' -> {arena, pool, period}."""
    out = {"arena": None, "pool": None, "period": 5.0}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, rhs = part.partition("=")
        if name not in ("arena", "pool"):
            raise ValueError(
                f"unknown mem_chaos key {name!r} (expected arena|pool)")
        fields = rhs.split(":")
        frac = float(fields[0])
        if not 0.0 < frac <= 1.0:
            raise ValueError(
                f"mem_chaos {name} fraction must be in (0, 1], got {frac}")
        out[name] = frac
        if len(fields) > 1:
            period = float(fields[1])
            if period <= 0:
                raise ValueError("mem_chaos period_s must be > 0")
            out["period"] = period
    if out["arena"] is None and out["pool"] is None:
        raise ValueError("mem_chaos spec squeezes neither arena nor pool")
    return out


class MemChaos:
    """Deterministic memory-budget squeeze planner.

        mc = MemChaos("arena=0.25:4,pool=0.5")
        frac = mc.arena_frac()   # 1.0 restored / 0.25 during a squeeze

    Consulted lazily on the hot paths it squeezes (agent admission/spill
    checks, engine page allocation) — no thread of its own, so it
    composes with ProcessChaos/LinkChaos without another tick loop.  The
    schedule starts RESTORED (first half-period at full budget) so
    cluster bring-up never races a squeeze.  Squeeze windows are
    reported into the shared :func:`memory_monitor.pressure_signal` so
    lease shedding and KV demotion see chaos pressure like any other."""

    def __init__(self, spec: str, seed: int = 0xC0FFEE):
        rule = parse_mem_spec(spec)
        self.arena = rule["arena"]
        self.pool = rule["pool"]
        self.period = rule["period"]
        self._rng = random.Random(seed)   # reserved for jittered schedules
        self._t0 = time.monotonic()
        self.squeezes = 0                 # completed squeeze windows seen

    def squeezing(self, now: Optional[float] = None) -> bool:
        t = (time.monotonic() if now is None else now) - self._t0
        cycle, phase = divmod(t, self.period)
        on = phase >= self.period / 2.0
        if on:
            self.squeezes = max(self.squeezes, int(cycle) + 1)
        return on

    def arena_frac(self, now: Optional[float] = None) -> float:
        if self.arena is None or not self.squeezing(now):
            return 1.0
        return self.arena

    def pool_frac(self, now: Optional[float] = None) -> float:
        if self.pool is None or not self.squeezing(now):
            return 1.0
        return self.pool

    def report_pressure(self) -> None:
        """Publish the current squeeze into the shared pressure signal
        (cleared when restored, so shed mode tracks the square wave)."""
        from . import memory_monitor
        sig = memory_monitor.pressure_signal()
        if self.squeezing():
            sig.report("chaos", 1.0 - min(self.arena_frac(),
                                          self.pool_frac()))
        else:
            sig.clear("chaos")


# log-file basename prefix -> process class
_LOG_CLASS = (("worker-", "worker"), ("agent_", "agent"),
              ("gcs.", "gcs"), ("zygote", "zygote"))


def parse_spec(spec: str) -> Dict[str, dict]:
    """'class=N:period_s[:delay_s],...' -> {class: rule dict}."""
    rules: Dict[str, dict] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, rhs = part.split("=")
        if name not in CLASSES:
            raise ValueError(
                f"unknown process_chaos class {name!r} "
                f"(expected one of {CLASSES})")
        fields = rhs.split(":")
        count = int(fields[0])
        period = float(fields[1]) if len(fields) > 1 else 5.0
        delay = float(fields[2]) if len(fields) > 2 else period
        rules[name] = {"left": count, "period": period, "delay": delay,
                       "due": None}
    return rules


class ProcessChaos:
    """SIGKILL supervisor for one cluster session (see module docstring).

        chaos = ProcessChaos("worker=2:2", session_dir).start()
        ...
        chaos.stop()

    ``restart`` maps a class to a zero-arg respawn callback invoked after
    each kill of that class (used for the GCS).  ``protect_pids`` are
    never killed (the driver and, typically, the head node's agent — the
    driver's object store lives there).  ``kills`` records
    (monotonic_ts, class, pid) per kill for assertions.
    """

    def __init__(self, spec: str, session_dir: str,
                 restart: Optional[Dict[str, Callable[[], None]]] = None,
                 protect_pids: Iterable[int] = (),
                 seed: int = 0xC0FFEE):
        self.rules = parse_spec(spec)
        self._log_dir = os.path.join(session_dir, "logs")
        self.restart = dict(restart or {})
        self.protect = set(protect_pids) | {os.getpid()}
        self._rng = random.Random(seed)
        self.kills: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle --
    def start(self) -> "ProcessChaos":
        # A rule's delay counts from the moment its class FIRST has a
        # killable candidate (rule["due"] is armed lazily in _loop), so
        # schedules are deterministic relative to the cluster actually
        # being up rather than to however long fixture setup took.
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ray_tpu_chaos")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def done(self) -> bool:
        """Every rule's kill budget is spent."""
        return all(rule["left"] <= 0 for rule in self.rules.values())

    # ------------------------------------------------------------ discovery --
    @staticmethod
    def _ppid(pid: int) -> Optional[int]:
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                # comm may contain ')': split on the LAST one.
                return int(f.read().rsplit(b")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            return None

    def _scan(self) -> Dict[int, str]:
        """pid -> class for this session's processes, classified by where
        fd 2 (stderr) points — robust for exec'd and zygote-forked
        processes alike."""
        procs: Dict[int, str] = {}
        prefix = self._log_dir + os.sep
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            pid = int(pid_s)
            if pid in self.protect:
                continue
            try:
                target = os.readlink(f"/proc/{pid}/fd/2")
            except OSError:
                continue
            if not target.startswith(prefix):
                continue
            base = os.path.basename(target)
            for log_prefix, cls in _LOG_CLASS:
                if base.startswith(log_prefix):
                    procs[pid] = cls
                    break
        return procs

    def _node_members(self, agent_pid: int, procs: Dict[int, str]) -> list:
        """Workers/zygotes whose ppid chain reaches agent_pid — killed
        together with the agent so an 'agent' kill behaves like losing
        the whole node (a preemption kills every process on the host)."""
        out = []
        for pid, cls in procs.items():
            if cls == "agent":
                continue
            p, hops = pid, 0
            while p is not None and p > 1 and hops < 8:
                p = self._ppid(p)
                hops += 1
                if p == agent_pid:
                    out.append(pid)
                    break
        return out

    # ----------------------------------------------------------------- loop --
    @staticmethod
    def _kill(pid: int) -> bool:
        try:
            os.kill(pid, signal.SIGKILL)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            procs = None          # at most ONE /proc walk per tick
            for cls, rule in self.rules.items():
                if rule["left"] <= 0:
                    continue
                if rule["due"] is not None and now < rule["due"]:
                    continue
                if procs is None:
                    procs = self._scan()
                cands = sorted(p for p, c in procs.items() if c == cls)
                if not cands:
                    continue          # nothing alive yet; retry next tick
                if rule["due"] is None:
                    # First candidate of this class just appeared: arm the
                    # schedule's initial delay from now.
                    rule["due"] = now + rule["delay"]
                    continue
                pid = cands[self._rng.randrange(len(cands))]
                extras = (self._node_members(pid, procs)
                          if cls == "agent" else [])
                if not self._kill(pid):
                    continue          # raced its exit; budget untouched
                for extra in extras:
                    self._kill(extra)
                rule["left"] -= 1
                rule["due"] = now + rule["period"]
                self.kills.append((now, cls, pid))
                logger.warning("chaos: SIGKILL %s pid=%d%s", cls, pid,
                               f" (+{len(extras)} node procs)"
                               if extras else "")
                cb = self.restart.get(cls)
                if cb is not None:
                    try:
                        cb()
                    except Exception:
                        logger.exception(
                            "chaos restart callback for %s failed", cls)
            self._stop.wait(0.1)
