"""Binary identifiers for jobs, tasks, actors, objects, nodes, and workers.

TPU-native re-design of the reference's ID scheme (reference: src/ray/common/id.h,
id_def.h). Like the reference, ObjectIDs embed the ID of the task that created
them plus a put/return index, and TaskIDs embed the job (and actor, if any) so
ownership metadata travels inside the ID itself. Sizes follow the reference:
JobID 4 bytes, ActorID 12, TaskID 16, ObjectID 20; NodeID/WorkerID 16 (the
reference uses 28-byte UniqueIDs; 16 random bytes carry the same collision
guarantees for realistic cluster sizes and halve header bytes on the wire).
"""

from __future__ import annotations

import os
import threading

_NIL = b"\x00"

# Fast random-byte source for ID minting: one 16-byte urandom seed plus a
# process-local counter, SHAKE-free (blake2b keyed digests). os.urandom is
# a syscall (~10us) and showed up at >1% of the task-submission profile;
# collision resistance only needs uniqueness within a cluster's lifetime,
# which the seeded-counter construction gives.
import hashlib

_seed = os.urandom(16)
_ctr = 0
_ctr_lock = threading.Lock()
_buf = b""
_pos = 0


def _reseed():
    global _seed, _ctr, _buf, _pos
    _seed = os.urandom(16)
    _ctr = 0
    _buf = b""
    _pos = 0


os.register_at_fork(after_in_child=_reseed)  # forked children must not
#                                              replay the parent's stream


def _rand(n: int) -> bytes:
    """n pseudo-random bytes from a buffered keyed-blake2b stream: one
    64-byte digest feeds ~8 IDs, so the per-ID cost is a slice + lock
    instead of a full hash (ID minting is on the task-submit hot path)."""
    global _ctr, _buf, _pos
    with _ctr_lock:
        pos = _pos
        if pos + n > len(_buf):
            _ctr += 1
            _buf = hashlib.blake2b(_ctr.to_bytes(8, "little"), key=_seed,
                                   digest_size=64).digest()
            pos = 0
        _pos = pos + n
        return _buf[pos:_pos]


class BaseID:
    __slots__ = ("_bin",)
    SIZE = 16

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bin = binary

    @classmethod
    def from_random(cls):
        return cls(_rand(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def is_nil(self) -> bool:
        return self._bin == _NIL * self.SIZE

    def __hash__(self):
        return hash(self._bin)

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __repr__(self):
        return f"{type(self).__name__}({self._bin.hex()})"


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int):
        return cls(value.to_bytes(4, "little"))

    def int(self) -> int:
        return int.from_bytes(self._bin, "little")


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 bytes: 8 random + 4 job id (mirrors reference ActorID layout)."""

    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID):
        return cls(_rand(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[8:12])


class TaskID(BaseID):
    """16 bytes, job id in the last 4.  Normal tasks carry 12 random bytes
    (96-bit entropy: the submit fast path mints ids at >10k/s, so the
    4-byte uniqueness the reference derives from (parent id, index) chains
    would birthday-collide within ~1e5 tasks); actor tasks carry 8 random
    + the actor's 4-byte random prefix.  The parent task is the *owner* of
    the task's return objects."""

    SIZE = 16

    @classmethod
    def for_normal_task(cls, job_id: JobID):
        return cls(_rand(12) + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID):
        return cls(_rand(8) + actor_id.binary()[:4]
                   + actor_id.job_id().binary())

    @classmethod
    def for_driver(cls, job_id: JobID):
        return cls(_NIL * 12 + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[12:16])


def fast_actor_task_id(actor_id_bin: bytes) -> bytes:
    """Binary TaskID for an actor task, minted without constructing the
    ActorID/TaskID wrappers (submit hot path: wrapper construction and
    re-slicing cost more than the ID's entropy).  Layout matches
    TaskID.for_actor_task: 8 random + actor[:4] + job (= actor[8:12])."""
    return _rand(8) + actor_id_bin[:4] + actor_id_bin[8:12]


class ObjectID(BaseID):
    """20 bytes: 16-byte task id of the creating task + 4-byte index.

    Index semantics follow the reference (common/id.h): return objects use
    indices 1..n; `put` objects use a separate counter sequence offset by
    2**31 so puts and returns never collide.
    """

    SIZE = 20
    PUT_INDEX_OFFSET = 1 << 31

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        return cls(
            task_id.binary() + (cls.PUT_INDEX_OFFSET + put_index).to_bytes(4, "little")
        )

    def task_id(self) -> TaskID:
        return TaskID(self._bin[:16])

    def index(self) -> int:
        return int.from_bytes(self._bin[16:20], "little")

    def is_put(self) -> bool:
        return self.index() >= self.PUT_INDEX_OFFSET


ObjectRefID = ObjectID  # alias


class _Counter:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v
