"""Black-box diagnosis plane: live introspection + hung-work watchdogs.

The flight recorder answers "what happened"; this module answers "what
is stuck RIGHT NOW and why" (reference: `ray stack` + the dashboard
reporter's py-spy/memray profiling, dashboard/modules/reporter/
profile_manager.py; design spirit: Ren et al., Google-Wide Profiling,
IEEE Micro 2010 — always-on sampling — and Dean & Barroso, The Tail at
Scale, CACM 2013 — capture the anomaly at the moment it happens).

Three layers, all composed from primitives that already exist:

* **Introspection helpers** — `dump_stacks()` / `cpu_profile()` are the
  shared implementations behind the worker's `stacks`/`cpu_profile`
  RPCs, the daemons' equivalents (`profile_handlers(tag)` registered on
  the existing GCS/agent conns), and the GCS `cluster_profile` fan-out.
  Results carry both human-readable tracebacks and collapsed
  ("folded") stacks so any subtree of the cluster merges into one
  flamegraph: `merge_cluster_profile()` → `folded_text()` /
  `speedscope_json()`.

* **Watchdog** — one daemon *thread* per process (a thread, not an
  asyncio task: it must keep running when the event loop it watches is
  wedged) polling cheap detectors: wedged loops (loopmon entry stale
  while its thread is alive → dump that thread via
  `sys._current_frames`), tasks RUNNING past a multiple of their
  function's historical p95 (`TaskHangTracker`, fed by the existing
  task-event stream), leases granted-but-never-RUNNING (agent-side),
  serving requests admitted-but-token-silent (serving-side).  Every
  firing goes through `record_anomaly()`: a typed `anomaly` recorder
  event + a `ray_tpu_anomaly_total{kind,...}` counter + an optional
  notify callback that forwards the anomaly to the GCS.

* **CaptureManager** — rate-limited per anomaly kind; the GCS uses it
  to write `diag-<kind>-<ts>/` black-box bundles (stacks, CPU profile,
  metrics, node views, recorder drain, manifest).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
import traceback
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from . import flight_recorder as frec
from . import loopmon
from .config import get_config

# Cap on stack text attached to anomaly events/recorder args (the ring
# and the telemetry blobs are bounded; a deep recursion dump is not).
_STACK_CAP = 8000


# ---------------------------------------------------------------------------
# stack / profile introspection helpers (shared by worker + daemons)
# ---------------------------------------------------------------------------

def _frame_folded(frame) -> str:
    """Collapse one Python frame chain into `root;...;leaf` folded form
    (same `<basename>:<line>:<func>` frame naming as the sampling
    profiler so stacks and profiles merge into the same flamegraphs)."""
    parts: List[str] = []
    while frame is not None:
        co = frame.f_code
        parts.append(f"{os.path.basename(co.co_filename)}:"
                     f"{frame.f_lineno}:{co.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


def dump_stacks() -> dict:
    """Every thread of THIS process: formatted traceback + folded stack.

    Wire shape: ``{"pid", "stacks": {label: text}, "folded":
    {label: "root;...;leaf"}}`` with label ``<thread-name>-<ident>``."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks: Dict[str, str] = {}
    folded: Dict[str, str] = {}
    for tid, frame in frames.items():
        label = f"{names.get(tid, '?')}-{tid}"
        stacks[label] = "".join(traceback.format_stack(frame))
        folded[label] = _frame_folded(frame)
    return {"pid": os.getpid(), "stacks": stacks, "folded": folded}


def dump_thread_stack(ident: Optional[int]) -> str:
    """One thread's current stack, dumped from a SIBLING thread — the
    wedged-loop detector's view into a frozen event loop."""
    if ident is None:
        return ""
    frame = sys._current_frames().get(ident)
    if frame is None:
        return ""
    return "".join(traceback.format_stack(frame))[-_STACK_CAP:]


async def cpu_profile(duration_s: float = 2.0,
                      interval_s: float = 0.01) -> dict:
    """Sampling CPU profile of THIS process (all threads), collapsed
    stacks with sample counts — py-spy-shaped, no native deps.

    Wire shape: ``{"pid", "samples", "stacks": [{"stack", "count"}]}``
    where each ``stack`` is already folded root→leaf."""
    import asyncio
    duration_s = min(float(duration_s), 60.0)
    interval_s = max(float(interval_s), 0.001)
    counts: Dict[str, int] = defaultdict(int)
    samples = 0
    deadline = time.monotonic() + duration_s
    while time.monotonic() < deadline:
        for _tid, frame in sys._current_frames().items():
            counts[_frame_folded(frame)] += 1
        samples += 1
        await asyncio.sleep(interval_s)
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:50]
    return {"pid": os.getpid(), "samples": samples,
            "stacks": [{"stack": s, "count": c} for s, c in top]}


def profile_handlers(tag: str) -> Dict[str, Callable]:
    """RPC handlers serving this process's own stacks/CPU profile —
    registered on the existing daemon conns (GCS server, agent server)
    so `cluster_profile` covers daemons, not just workers."""
    async def h_stacks(conn, p):
        out = dump_stacks()
        out["daemon"] = tag
        return out

    async def h_cpu_profile(conn, p):
        out = await cpu_profile(p.get("duration_s", 2.0),
                                p.get("interval_s", 0.01))
        out["daemon"] = tag
        return out

    return {"stacks": h_stacks, "cpu_profile": h_cpu_profile}


# ---------------------------------------------------------------------------
# flamegraph rendering: folded merge -> folded text / speedscope JSON
# ---------------------------------------------------------------------------

def _proc_folded(result: dict, kind: str, prefix: str,
                 out: Dict[str, int]) -> None:
    if not isinstance(result, dict) or result.get("error"):
        return
    if kind == "stacks":
        for label, folded in (result.get("folded") or {}).items():
            if folded:
                out[f"{prefix};{label};{folded}"] += 1
    else:
        for row in result.get("stacks") or []:
            if row.get("stack"):
                out[f"{prefix};{row['stack']}"] += int(row.get("count", 1))


def merge_cluster_profile(merged: dict) -> Dict[str, int]:
    """Flatten a `cluster_profile` result tree into one folded mapping
    ``"proc;frame;...;leaf" -> weight`` (weight = 1 per thread for
    stacks, sample count for cpu_profile).  Process roots are
    ``gcs``, ``node-<hex8>/agent``, ``node-<hex8>/worker-<hex8>``."""
    kind = merged.get("kind", "stacks")
    out: Dict[str, int] = defaultdict(int)
    if merged.get("gcs"):
        _proc_folded(merged["gcs"], kind, "gcs", out)
    for node_hex, node in (merged.get("nodes") or {}).items():
        if not isinstance(node, dict):
            continue
        root = f"node-{node_hex[:8]}"
        if node.get("agent"):
            _proc_folded(node["agent"], kind, f"{root}/agent", out)
        for wid, wres in (node.get("workers") or {}).items():
            _proc_folded(wres, kind, f"{root}/worker-{wid[:8]}", out)
    return dict(out)


def folded_text(folded: Dict[str, int]) -> str:
    """Brendan-Gregg collapsed-stack text: one `stack count` per line
    (feedable to flamegraph.pl / speedscope / inferno)."""
    lines = [f"{stack} {count}"
             for stack, count in sorted(folded.items(),
                                        key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def speedscope_json(folded: Dict[str, int], name: str = "ray_tpu") -> dict:
    """Render a folded mapping as a speedscope sampled profile
    (https://www.speedscope.app/file-format-schema.json)."""
    frame_index: Dict[str, int] = {}
    frames: List[dict] = []
    samples: List[List[int]] = []
    weights: List[int] = []
    for stack, count in sorted(folded.items()):
        idxs = []
        for fr in stack.split(";"):
            if fr not in frame_index:
                frame_index[fr] = len(frames)
                frames.append({"name": fr})
            idxs.append(frame_index[fr])
        samples.append(idxs)
        weights.append(int(count))
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "activeProfileIndex": 0,
        "exporter": "ray_tpu-diagnosis",
        "name": name,
    }


# ---------------------------------------------------------------------------
# anomaly emission
# ---------------------------------------------------------------------------

_counter_lock = threading.Lock()
_anomaly_counter = None


def _counter():
    global _anomaly_counter
    with _counter_lock:
        if _anomaly_counter is None:
            from ..util.metrics import Counter
            _anomaly_counter = Counter(
                "ray_tpu_anomaly_total",
                "hung-work detector firings by kind",
                tag_keys=("kind", "daemon", "node_id"))
        return _anomaly_counter


def record_anomaly(kind: str, *, daemon: str, node_id: str = "",
                   notify: Optional[Callable[[dict], None]] = None,
                   **details) -> dict:
    """One detector firing: typed recorder event + node-labeled counter
    + optional forward to the GCS (best-effort, thread-safe via the
    caller-provided callback).  Returns the anomaly dict."""
    info = {"kind": kind, "daemon": daemon, "node_id": node_id,
            "ts": time.time(), **details}
    try:
        _counter().inc(1, tags={"kind": kind, "daemon": daemon,
                                "node_id": node_id})
    except Exception:
        pass
    try:
        # Detail keys that shadow instant()'s own parameters (a task_hung
        # detail carries the task's function NAME) get a trailing "_" so
        # they ride as event args instead of raising TypeError.
        args = {(f"{k}_" if k in ("cat", "name", "id") else k):
                (v[-_STACK_CAP:] if isinstance(v, str) else v)
                for k, v in details.items()}
        frec.recorder().instant("anomaly", f"anomaly:{kind}", **args)
    except Exception:
        pass
    if notify is not None:
        try:
            notify(info)
        except Exception:
            pass
    return info


class Watchdog(threading.Thread):
    """Per-daemon hung-work watchdog.

    A plain daemon THREAD (never an asyncio task — its whole job is to
    keep observing when the event loop is wedged) that polls a list of
    detectors.  A detector is a callable returning a list of anomaly
    dicts (``{"kind": ..., **details}``); each is routed through
    `record_anomaly` with this daemon's identity and notify callback."""

    def __init__(self, *, daemon_name: str, node_id: str = "",
                 detectors: List[Callable[[], List[dict]]],
                 notify: Optional[Callable[[dict], None]] = None,
                 poll_s: float = 0.5):
        super().__init__(name=f"diag-watchdog-{daemon_name}", daemon=True)
        self.daemon_name = daemon_name
        self.node_id = node_id
        self.detectors = list(detectors)
        self.notify = notify
        self.poll_s = max(0.05, float(poll_s))
        self._stop_evt = threading.Event()
        self.fired: List[dict] = []

    def stop(self) -> None:
        self._stop_evt.set()

    def poll_once(self) -> List[dict]:
        out = []
        for det in self.detectors:
            try:
                anomalies = det() or []
            except Exception:
                continue
            for a in anomalies:
                kind = a.pop("kind", "unknown")
                info = record_anomaly(kind, daemon=self.daemon_name,
                                      node_id=self.node_id,
                                      notify=self.notify, **a)
                out.append(info)
        self.fired.extend(out)
        del self.fired[:-64]
        return out

    def run(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            self.poll_once()


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------

def loop_wedge_detector(threshold_s: Optional[float] = None
                        ) -> Callable[[], List[dict]]:
    """Wedged event loops: loopmon entry stale >= threshold while the
    loop's thread is still alive (stale+dead = stopped, not wedged).
    Re-emits at most once per threshold while the wedge persists, so a
    long wedge counts as repeated flaps without per-poll spam."""
    last_emit: Dict[str, float] = {}

    def check() -> List[dict]:
        thr = threshold_s
        if thr is None:
            thr = get_config().diagnosis_loop_wedge_s
        out = []
        now = time.monotonic()
        for label, info in loopmon.snapshot_full().items():
            if info["stale_s"] < thr or not info.get("alive"):
                last_emit.pop(label, None)
                continue
            if now - last_emit.get(label, -1e9) < thr:
                continue
            last_emit[label] = now
            out.append({"kind": "loop_wedged", "loop": label,
                        "stale_s": round(info["stale_s"], 3),
                        "stack": dump_thread_stack(info["thread_ident"])})
        return out

    return check


class TaskHangTracker:
    """Per-function execution-time EMA + hung-RUNNING detection.

    Fed from the existing task-event stream (core_worker's
    `record_task_event` — one extra method call on a path that already
    buffers an event): RUNNING starts tracking, a terminal event stops
    it and folds the duration into the function's p95 estimate
    (asymmetric EMA: jumps up fast, decays down slowly — the
    conservative direction for a hang threshold)."""

    _TERMINAL = ("FINISHED", "FAILED", "CANCELLED")

    def __init__(self, *, multiple: float = 20.0, min_s: float = 10.0,
                 default_s: float = 120.0,
                 thread_lookup: Optional[Callable[[bytes],
                                                  Optional[int]]] = None):
        self.multiple = multiple
        self.min_s = min_s
        self.default_s = default_s
        self.thread_lookup = thread_lookup
        self._lock = threading.Lock()
        self._running: Dict[bytes, tuple] = {}   # task_id -> (t0, name)
        self._p95: Dict[str, float] = {}
        self._flagged: set = set()
        self._tasks_started = 0
        self._last_started: Optional[float] = None

    def note(self, task_id: bytes, name: str, event: str) -> None:
        if event == "RUNNING":
            with self._lock:
                self._running[task_id] = (time.monotonic(), name)
                self._tasks_started += 1
                self._last_started = time.monotonic()
        elif event in self._TERMINAL:
            with self._lock:
                ent = self._running.pop(task_id, None)
                self._flagged.discard(task_id)
                if ent is None or event != "FINISHED":
                    return
                dur = time.monotonic() - ent[0]
                prev = self._p95.get(name)
                if prev is None:
                    self._p95[name] = dur
                elif dur > prev:
                    self._p95[name] = 0.5 * prev + 0.5 * dur
                else:
                    self._p95[name] = 0.95 * prev + 0.05 * dur
                if len(self._p95) > 512:
                    self._p95.pop(next(iter(self._p95)))

    def threshold_for(self, name: str) -> float:
        p95 = self._p95.get(name)
        if p95 is None:
            return self.default_s
        return max(self.multiple * p95, self.min_s)

    def stats(self) -> dict:
        """Cheap executor-activity summary (the agent's lease-stall
        detector probes this over the existing worker conn)."""
        with self._lock:
            now = time.monotonic()
            return {
                "running": len(self._running),
                "tasks_started": self._tasks_started,
                "last_task_started_age_s":
                    (now - self._last_started
                     if self._last_started is not None else None),
                "oldest_running_age_s":
                    (now - min(t0 for t0, _ in self._running.values())
                     if self._running else None),
            }

    def detector(self) -> Callable[[], List[dict]]:
        def check() -> List[dict]:
            now = time.monotonic()
            out = []
            with self._lock:
                items = list(self._running.items())
            for task_id, (t0, name) in items:
                age = now - t0
                if age < self.threshold_for(name):
                    continue
                with self._lock:
                    if (task_id in self._flagged
                            or task_id not in self._running):
                        continue
                    self._flagged.add(task_id)
                stack = ""
                if self.thread_lookup is not None:
                    stack = dump_thread_stack(self.thread_lookup(task_id))
                out.append({"kind": "task_hung", "task_id": task_id.hex(),
                            "name": name, "running_s": round(age, 3),
                            "threshold_s": round(self.threshold_for(name), 3),
                            "stack": stack})
            return out
        return check


_task_tracker: Optional[TaskHangTracker] = None


def init_task_tracker(**kw) -> TaskHangTracker:
    global _task_tracker
    _task_tracker = TaskHangTracker(**kw)
    return _task_tracker


def task_tracker() -> Optional[TaskHangTracker]:
    return _task_tracker


# ---------------------------------------------------------------------------
# black-box capture bundles
# ---------------------------------------------------------------------------

def _jsonable(obj):
    if isinstance(obj, dict):
        return {(k.hex() if isinstance(k, bytes) else str(k)): _jsonable(v)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


class CaptureManager:
    """Writes `diag-<kind>-<ts>/` bundle dirs, rate-limited per anomaly
    kind so a flapping detector keeps counting without DoSing the
    cluster with bundle I/O."""

    def __init__(self, root: str, *, min_interval_s: float = 60.0,
                 max_bundles: int = 20):
        self.root = root
        self.min_interval_s = min_interval_s
        self.max_bundles = max_bundles
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}
        self.suppressed: Dict[str, int] = defaultdict(int)

    def should_capture(self, kind: str, *, force: bool = False) -> bool:
        """Check + stamp the rate limit for `kind`.  A suppressed flap
        is counted (`suppressed`), never silently dropped."""
        with self._lock:
            now = time.monotonic()
            if not force and now - self._last.get(kind, -1e9) \
                    < self.min_interval_s:
                self.suppressed[kind] += 1
                return False
            self._last[kind] = now
            return True

    def write_bundle(self, kind: str, parts: Dict[str, Any],
                     manifest_extra: Optional[dict] = None) -> str:
        """One timestamped bundle dir: each part as <name>.json plus a
        manifest.json describing what was captured and why."""
        ts = time.strftime("%Y%m%d_%H%M%S")
        base = f"diag-{kind}-{ts}"
        path = os.path.join(self.root, base)
        n = 1
        while os.path.exists(path):
            path = os.path.join(self.root, f"{base}_{n}")
            n += 1
        os.makedirs(path, exist_ok=True)
        files = []
        for name, content in parts.items():
            fname = f"{name}.json"
            with open(os.path.join(path, fname), "w") as f:
                json.dump(_jsonable(content), f, indent=1)
            files.append(fname)
        manifest = {"anomaly_kind": kind, "captured_at": time.time(),
                    "files": sorted(files),
                    "suppressed_since_last": self.suppressed.get(kind, 0),
                    "anomaly": _jsonable(manifest_extra or {})}
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        self._prune()
        return path

    def _prune(self) -> None:
        try:
            bundles = sorted(
                d for d in os.listdir(self.root) if d.startswith("diag-"))
        except OSError:
            return
        for stale in bundles[:-self.max_bundles]:
            shutil.rmtree(os.path.join(self.root, stale),
                          ignore_errors=True)
