"""ctypes binding to the native shared-memory object store (src/object_store).

Equivalent of the reference's plasma client (reference:
src/ray/object_manager/plasma/client.h) — but daemonless: every process maps
the same /dev/shm arena and calls into the native library under a
process-shared robust mutex, giving zero-copy create/seal/get without a socket
round trip. The library is built on first use with g++ (no pip deps).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading

from . import native_build

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "object_store", "store.cc")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_shmstore.so")

_build_lock = threading.Lock()
_lib = None


def _ensure_built() -> str:
    # Load-bearing (the store IS the data plane): a failed rebuild with
    # no usable committed artifact raises.  With one present, fall back
    # to it — a compiler-less host must keep running on the committed
    # binary even when checkout mtimes suggest staleness.
    with _build_lock:
        return native_build.build_so(_SRC, _SO, ldflags=("-lpthread",),
                                     fallback_to_stale=True)


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_ensure_built())
    lib.rts_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.rts_create.restype = ctypes.c_int
    lib.rts_attach.argtypes = [ctypes.c_char_p]
    lib.rts_attach.restype = ctypes.c_int
    lib.rts_detach.argtypes = [ctypes.c_int]
    lib.rts_data_offset.argtypes = [ctypes.c_int]
    lib.rts_data_offset.restype = ctypes.c_uint64
    lib.rts_capacity.argtypes = [ctypes.c_int]
    lib.rts_capacity.restype = ctypes.c_uint64
    lib.rts_total_size.argtypes = [ctypes.c_int]
    lib.rts_total_size.restype = ctypes.c_uint64
    lib.rts_create_object.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64]
    lib.rts_create_object.restype = ctypes.c_int64
    lib.rts_seal.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_seal.restype = ctypes.c_int
    lib.rts_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    lib.rts_get.restype = ctypes.c_int64
    lib.rts_release.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_release.restype = ctypes.c_int
    lib.rts_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_delete.restype = ctypes.c_int
    lib.rts_contains.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_contains.restype = ctypes.c_int
    lib.rts_abort.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_abort.restype = ctypes.c_int
    lib.rts_refcount.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_refcount.restype = ctypes.c_int
    lib.rts_release_n_and_delete_if.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.rts_release_n_and_delete_if.restype = ctypes.c_int
    lib.rts_stats.argtypes = [ctypes.c_int] + [ctypes.POINTER(ctypes.c_uint64)] * 5
    lib.rts_list_evictable.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.rts_list_evictable.restype = ctypes.c_int
    lib.rts_list_objects.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.rts_list_objects.restype = ctypes.c_int
    lib.rts_list_unsealed.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.rts_list_unsealed.restype = ctypes.c_int
    lib.rts_put_iov.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_void_p),
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.rts_put_iov.restype = ctypes.c_int
    lib.rts_chan_init.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                  ctypes.c_uint32, ctypes.c_uint64,
                                  ctypes.c_uint32]
    lib.rts_chan_init.restype = ctypes.c_int64
    lib.rts_chan_write.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                   ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_int]
    lib.rts_chan_write.restype = ctypes.c_int
    lib.rts_chan_peek.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                  ctypes.c_uint32,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_int]
    lib.rts_chan_peek.restype = ctypes.c_int
    lib.rts_chan_advance.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                     ctypes.c_uint32]
    lib.rts_chan_advance.restype = ctypes.c_int
    lib.rts_chan_close.argtypes = [ctypes.c_int, ctypes.c_uint64]
    lib.rts_chan_close.restype = ctypes.c_int
    lib.rts_chan_destroy.argtypes = [ctypes.c_int, ctypes.c_char_p]
    lib.rts_chan_destroy.restype = ctypes.c_int
    _lib = lib
    return lib


class ShmObjectStoreError(Exception):
    pass


class SpillTruncatedError(OSError):
    """A spill file is shorter than the object it records — the on-disk
    copy itself is damaged (vs a transient I/O error, which must NOT be
    treated as corruption)."""


class ObjectExistsError(ShmObjectStoreError):
    pass


class StoreFullError(ShmObjectStoreError):
    pass


class ShmStore:
    """A client attachment to one node's shared-memory arena."""

    def __init__(self, path: str, handle: int):
        self._lib = _load()
        self.path = path
        self._h = handle
        total = self._lib.rts_total_size(handle)
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def create(cls, path: str, capacity: int, table_slots: int = 1 << 16) -> "ShmStore":
        lib = _load()
        h = lib.rts_create(path.encode(), capacity, table_slots)
        if h < 0:
            raise ShmObjectStoreError(f"create failed: errno {-h}")
        return cls(path, h)

    @classmethod
    def attach(cls, path: str) -> "ShmStore":
        lib = _load()
        h = lib.rts_attach(path.encode())
        if h < 0:
            raise ShmObjectStoreError(f"attach failed: errno {-h}")
        return cls(path, h)

    def close(self):
        """Detach. If zero-copy views handed out by `get` are still alive the
        mapping is left in place (it is reclaimed at process exit), matching
        plasma-client semantics where buffers outlive the client."""
        if self._h is not None:
            try:
                self._view.release()
                self._mm.close()
                self._lib.rts_detach(self._h)
            except BufferError:
                pass
            self._h = None

    # -- object ops ----------------------------------------------------------
    def create_buffer(self, object_id: bytes, size: int) -> memoryview:
        """Allocate an unsealed object; returns a writable view of its bytes.

        Contract the data plane depends on: the view (and any slice of
        it) is a C-contiguous writable memoryview over the arena mmap.
        rpc.Connection's native recv takeover uses exactly this to
        `recv()` pull chunks straight into the region
        (ctypes.from_buffer needs writable+contiguous), and RawPayload
        serving hands slices of `get` views to writev the same way —
        changing the backing to anything non-contiguous would silently
        demote bulk transfers to the buffered path."""
        off = self._lib.rts_create_object(self._h, object_id, size)
        if off == -17:  # EEXIST
            raise ObjectExistsError(object_id.hex())
        if off < 0:
            raise StoreFullError(f"alloc {size} failed: errno {-off}")
        return self._view[off:off + size]

    # Parallel-memcpy width for rts_put_iov (threads engage >= 32 MiB).
    _COPY_THREADS = min(8, os.cpu_count() or 1)

    def put(self, object_id: bytes, payloads, keep_pin: bool = False) -> None:
        """Create + copy + seal (+ drop the writer's pin) in one native
        call. `payloads` is a list of buffer-like chunks concatenated into
        the object. The whole operation runs in C with the GIL released
        (ctypes), so a multi-hundred-MB put no longer stalls the caller's
        event loop; destination pages are batch-faulted and the copy
        parallelizes for large objects. With keep_pin=False the object is
        immediately evictable unless pinned via `get` (owner pinning is
        the object-manager layer's job, as in the reference's raylet
        PinObjectIDs); keep_pin=True leaves the writer's refcount in
        place so the pin can be transferred to the node agent without an
        evictable window (see core_worker pin-transfer)."""
        import numpy as np
        n = len(payloads)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keepalive = []
        for i, p in enumerate(payloads):
            try:
                a = p if isinstance(p, np.ndarray) \
                    else np.frombuffer(p, np.uint8)
            except ValueError:      # non-contiguous exotic buffer
                a = np.frombuffer(bytes(p), np.uint8)
            if not a.flags["C_CONTIGUOUS"]:
                a = np.ascontiguousarray(a)
            keepalive.append(a)
            ptrs[i] = a.ctypes.data
            lens[i] = a.nbytes
        rc = self._lib.rts_put_iov(self._h, object_id, ptrs, lens, n,
                                   self._COPY_THREADS,
                                   1 if keep_pin else 0)
        del keepalive
        if rc == -17:  # EEXIST
            raise ObjectExistsError(object_id.hex())
        if rc < 0:
            raise StoreFullError(f"put failed: errno {-rc}")

    def seal(self, object_id: bytes) -> None:
        rc = self._lib.rts_seal(self._h, object_id)
        if rc < 0 and rc != -114:  # EALREADY ok
            raise ShmObjectStoreError(f"seal failed: errno {-rc}")

    def read_file_into(self, object_id: bytes, path: str, size: int,
                       keep_pin: bool = False) -> None:
        """Spill-restore fast path: allocate the object and read the spill
        file DIRECTLY into its arena view (readinto — one copy from the
        page cache, no intermediate Python bytes), then seal.  With
        keep_pin=False the writer pin drops at seal; keep_pin=True leaves
        it in place so the caller can transfer pins without an evictable
        window.  Raises StoreFullError/ObjectExistsError like
        create_buffer; aborts the allocation on a read failure."""
        # Open FIRST: a missing spill file must surface as
        # FileNotFoundError (-> external-tier fallback), not as whatever
        # the arena allocation would raise under memory pressure.
        with open(path, "rb", buffering=0) as f:
            buf = self.create_buffer(object_id, size)
            try:
                got = 0
                while got < size:
                    n = f.readinto(buf[got:])   # raw read: may be short
                    if not n:
                        break
                    got += n
                if got != size:
                    raise SpillTruncatedError(
                        f"spill file {path} truncated: {got}/{size} bytes")
                buf.release()
                self.seal(object_id)
                if not keep_pin:
                    self.release(object_id)
            except BaseException:
                buf.release()           # idempotent
                self.abort(object_id)
                raise

    def get(self, object_id: bytes, timeout_ms: int = 0) -> memoryview | None:
        """Returns a zero-copy readonly view, or None if absent/timeout.
        Pins the object until `release`."""
        size = ctypes.c_uint64()
        off = self._lib.rts_get(self._h, object_id, ctypes.byref(size), timeout_ms)
        if off < 0:
            return None
        return self._view[off:off + size.value].toreadonly()

    def release(self, object_id: bytes) -> None:
        self._lib.rts_release(self._h, object_id)

    def delete(self, object_id: bytes) -> bool:
        return self._lib.rts_delete(self._h, object_id) == 0

    def abort(self, object_id: bytes) -> bool:
        return self._lib.rts_abort(self._h, object_id) == 0

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.rts_contains(self._h, object_id))

    def refcount(self, object_id: bytes) -> int:
        """Pin count of a sealed object; -1 if absent."""
        rc = self._lib.rts_refcount(self._h, object_id)
        return rc if rc >= 0 else -1

    def release_n_and_delete_if(self, object_id: bytes, n: int) -> bool:
        """Spill commit: release our n pins and delete iff no other reader
        holds a pin (atomic). False = a reader appeared; only the read pin
        was dropped and the object stays resident."""
        return self._lib.rts_release_n_and_delete_if(
            self._h, object_id, n) == 0

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(5)]
        self._lib.rts_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {
            "bytes_in_use": vals[0].value,
            "num_objects": vals[1].value,
            "num_evictions": vals[2].value,
            "bytes_evicted": vals[3].value,
            "capacity": vals[4].value,
        }

    def list_objects(self, max_ids: int = 4096) -> list[tuple]:
        """(object_id, size, refcount) snapshot of every sealed object —
        feeds the state API's `list objects`."""
        rec = 20 + 12
        buf = ctypes.create_string_buffer(rec * max_ids)
        n = self._lib.rts_list_objects(self._h, buf, max_ids)
        raw = buf.raw
        out = []
        for i in range(n):
            p = raw[i * rec:(i + 1) * rec]
            out.append((p[:20], int.from_bytes(p[20:28], "little"),
                        int.from_bytes(p[28:32], "little")))
        return out

    def list_unsealed(self, max_ids: int = 4096) -> list[tuple]:
        """(object_id, size) snapshot of allocated-but-unsealed slots —
        orphan candidates when their writer died mid-copy (reclaim with
        abort())."""
        rec = 20 + 8
        buf = ctypes.create_string_buffer(rec * max_ids)
        n = self._lib.rts_list_unsealed(self._h, buf, max_ids)
        raw = buf.raw
        return [(raw[i * rec:i * rec + 20],
                 int.from_bytes(raw[i * rec + 20:i * rec + 28], "little"))
                for i in range(n)]

    def list_evictable(self, max_ids: int = 1024) -> list[bytes]:
        buf = ctypes.create_string_buffer(20 * max_ids)
        n = self._lib.rts_list_evictable(self._h, buf, max_ids)
        raw = buf.raw
        return [raw[i * 20:(i + 1) * 20] for i in range(n)]


class ChannelClosed(ShmObjectStoreError):
    pass


class Channel:
    """Mutable single-writer multi-reader ring channel inside the arena
    (reference: python/ray/experimental/channel/shared_memory_channel.py
    backed by experimental_mutable_object_manager.cc).  A write is a
    memcpy + futex wake; a read is a futex wait + copy-out — the compiled
    graph's per-step transport.  Use `create` once (the creator's pin
    keeps it alive), `attach` from each endpoint process."""

    def __init__(self, store: "ShmStore", channel_id: bytes, offset: int,
                 attached: bool):
        self._store = store
        self._lib = store._lib
        self.channel_id = channel_id
        self._off = offset
        self._attached = attached   # holds a get() pin to drop on close

    @classmethod
    def create(cls, store: "ShmStore", channel_id: bytes, *,
               nslots: int = 8, slot_bytes: int = 1 << 20,
               nreaders: int = 1) -> "Channel":
        off = store._lib.rts_chan_init(store._h, channel_id, nslots,
                                       slot_bytes, nreaders)
        if off < 0:
            raise StoreFullError(f"channel create failed: errno {-off}")
        return cls(store, channel_id, off, attached=False)

    @classmethod
    def attach(cls, store: "ShmStore", channel_id: bytes,
               timeout_ms: int = 10_000) -> "Channel":
        size = ctypes.c_uint64()
        off = store._lib.rts_get(store._h, channel_id,
                                 ctypes.byref(size), timeout_ms)
        if off < 0:
            raise ShmObjectStoreError(
                f"channel {channel_id.hex()} not found")
        return cls(store, channel_id, off, attached=True)

    def write(self, data: bytes, timeout_ms: int = -1) -> None:
        rc = self._lib.rts_chan_write(self._store._h, self._off, data,
                                      len(data), timeout_ms)
        if rc == -32:        # EPIPE
            raise ChannelClosed(self.channel_id.hex())
        if rc == -90:        # EMSGSIZE
            raise ValueError(
                f"message of {len(data)} bytes exceeds the channel slot "
                f"size; recompile the DAG with a larger slot_bytes")
        if rc == -110:       # ETIMEDOUT
            raise TimeoutError("channel write timed out (ring full)")
        if rc < 0:
            raise ShmObjectStoreError(f"channel write: errno {-rc}")

    def read(self, reader: int = 0, timeout_ms: int = -1) -> bytes:
        """Next message for `reader` (copied out — the ring slot is reused
        as soon as we advance). Raises ChannelClosed when closed+drained."""
        moff = ctypes.c_uint64()
        mlen = ctypes.c_uint64()
        rc = self._lib.rts_chan_peek(self._store._h, self._off, reader,
                                     ctypes.byref(moff), ctypes.byref(mlen),
                                     timeout_ms)
        if rc == -32:
            raise ChannelClosed(self.channel_id.hex())
        if rc == -110:
            raise TimeoutError("channel read timed out")
        if rc < 0:
            raise ShmObjectStoreError(f"channel read: errno {-rc}")
        data = bytes(self._store._view[moff.value:moff.value + mlen.value])
        self._lib.rts_chan_advance(self._store._h, self._off, reader)
        return data

    # C ChanHdr field offsets (store.cc): magic u32@0, nslots u32@4,
    # slot_bytes u64@8, nreaders u32@16, closed u32@20, wfutex u32@24,
    # rfutex u32@28, wseq u64@32, rseq u64[8]@40; ring data at
    # align_up(sizeof(ChanHdr)=104, kAlign=64) = 128, slot stride
    # align_up(8 + slot_bytes, 64).
    _HDR_DATA_OFF = 128

    def stats(self) -> dict:
        """Unsynchronized header snapshot: write/read sequence numbers and
        ring occupancy (wseq - slowest reader).  Races with concurrent
        endpoints are benign (torn reads impossible: each field is one
        aligned word) — occupancy gauges and teardown draining use this."""
        import struct
        v = self._store._view
        nslots, = struct.unpack_from("<I", v, self._off + 4)
        slot_bytes, = struct.unpack_from("<Q", v, self._off + 8)
        nreaders, closed = struct.unpack_from("<II", v, self._off + 16)
        wseq, = struct.unpack_from("<Q", v, self._off + 32)
        rseq = list(struct.unpack_from("<8Q", v, self._off + 40))[:nreaders]
        return {"nslots": nslots, "slot_bytes": slot_bytes,
                "nreaders": nreaders, "closed": bool(closed), "wseq": wseq,
                "rseq": rseq,
                "occupancy": wseq - (min(rseq) if rseq else 0)}

    def peek_at(self, seq: int) -> bytes:
        """Copy out the message at absolute write-sequence `seq` WITHOUT
        consuming it.  Only meaningful while `seq` is still resident
        (within nslots of wseq) and the ring is quiescent — the teardown
        spill-pin drain is the only caller."""
        import struct
        st = self.stats()
        stride = (8 + st["slot_bytes"] + 63) & ~63
        base = self._off + self._HDR_DATA_OFF + (seq % st["nslots"]) * stride
        mlen, = struct.unpack_from("<Q", self._store._view, base)
        return bytes(self._store._view[base + 8:base + 8 + mlen])

    def close(self) -> None:
        """Signal EOF to all endpoints (idempotent; does not free)."""
        self._lib.rts_chan_close(self._store._h, self._off)
        if self._attached:
            self._store.release(self.channel_id)
            self._attached = False

    def destroy(self) -> None:
        """Creator-side: close + free the backing object."""
        self._lib.rts_chan_destroy(self._store._h, self.channel_id)
