"""Cluster flight recorder: a low-overhead in-process event ring.

Reference surface: Ray's dedicated observability substrate —
src/ray/util/event.h (bounded in-memory event buffers per process),
core_worker/task_event_buffer.h (buffered status/profile events flushed
on an interval), src/ray/stats/ (per-process metric registry exported
via per-node agents).  TPU-native design collapses those into one
primitive: every daemon and worker owns a preallocated ring of
(mono-ns, category, name, payload) records; recording is one list-slot
store + index bump under a plain lock (any thread, no allocation beyond
the record tuple); flushes ride the process's EXISTING periodic push —
the core worker's telemetry loop and the agent's heartbeat tick — as
rows of the same GCS task-event sink the timeline already renders.  No
new per-event RPCs, ever.

Categories ("plane" granularity, gated via config
`flight_recorder_categories`, default all-on):

    lease      lease lifecycle on the agent (queued -> granted ->
               prefetch), extending the existing PREFETCH task event
    transfer   object-plane timelines: pull start/commit, chunk-wave
               stream, hedge fired, swarm source set
    sched      submit-side plane (reserved; SUBMITTED task events
               already cover the per-task view)
    request    LLM serving lifecycle (llm/serving.py + llm/engine.py):
               request:admit, prefill (w/ cached_tokens), decode
               (per tick, w/ batch), sample_sync, request:cancelled
    anomaly    diagnosis-plane detector firings (_private/diagnosis.py):
               loop_wedged, task_hung, lease_stalled, serving_silent —
               rendered as global instant marks on the timeline

Overflow drops the OLDEST record and counts it (`dropped`) — the
counter is exported as a metric and stamped into every flush, so a
truncated view is never mistaken for a complete one (same contract the
task-event sink satellite adds GCS-side).  `flight_recorder_sample_n`
keeps high-rate categories cheap: record 1 of every N `instant()`s per
category (spans are never sampled away — their rate is bounded by the
operations they wrap).

Timestamps are MONOTONIC ns at record time; `drain()` converts to this
process's wall clock (clocks.wall(), so injected chaos skew shifts them
like every other stamp) — cross-node alignment happens read-side from
the GCS-estimated per-node offsets (see clocks.py / timeline.py).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, List, Optional

from . import clocks


class FlightRecorder:
    def __init__(self, capacity: int = 4096,
                 categories: Optional[set] = None,
                 sample_n: int = 1,
                 enabled: bool = True):
        self.capacity = max(16, int(capacity))
        # Preallocated ring: slot stores are O(1) and the steady-state
        # allocation per event is just its record tuple.
        self._ring: list = [None] * self.capacity
        self._head = 0          # next write slot
        self._count = 0         # live records (<= capacity)
        self._lock = threading.Lock()
        self._categories = categories            # None = all
        self._sample_n = max(1, int(sample_n))
        self._sample_ctr: Dict[str, int] = {}
        self.enabled = enabled
        self.recorded = 0       # accepted records (monotonic)
        self.dropped = 0        # overwritten-before-flush records
        self.sampled_out = 0    # instants skipped by sampling

    # ------------------------------------------------------------ record --
    def active(self, cat: str) -> bool:
        return self.enabled and (self._categories is None
                                 or cat in self._categories)

    def _push(self, rec: tuple) -> None:
        with self._lock:
            if self._count == self.capacity:
                self.dropped += 1       # overwriting the oldest
            else:
                self._count += 1
            self._ring[self._head] = rec
            self._head = (self._head + 1) % self.capacity
            self.recorded += 1

    def instant(self, cat: str, name: str, id: bytes = b"",
                **args) -> None:
        """Point event.  Subject to per-category 1-in-N sampling."""
        if not self.active(cat):
            return
        if self._sample_n > 1:
            c = self._sample_ctr.get(cat, 0)
            self._sample_ctr[cat] = c + 1
            if c % self._sample_n:
                self.sampled_out += 1
                return
        t = clocks.mono_ns()
        self._push((t, t, cat, name, id, args or None))

    def begin(self) -> int:
        """Start stamp for a span; pass to end()."""
        return clocks.mono_ns()

    def end(self, cat: str, name: str, t0_ns: int, id: bytes = b"",
            **args) -> None:
        """Complete a span started at begin().  Spans are never sampled
        away — their rate is bounded by the operation they wrap."""
        if not self.active(cat):
            return
        self._push((t0_ns, clocks.mono_ns(), cat, name, id, args or None))

    @contextmanager
    def span(self, cat: str, name: str, id: bytes = b"", **args):
        if not self.active(cat):
            yield
            return
        t0 = clocks.mono_ns()
        try:
            yield
        finally:
            self._push((t0, clocks.mono_ns(), cat, name, id,
                        args or None))

    # ------------------------------------------------------------- flush --
    def drain(self, node_id: bytes = b"",
              worker_id: bytes = b"") -> List[dict]:
        """Swap the ring out and convert records to task-event-sink rows
        (event='SPAN', cat=<plane>) ready to ride an existing batched
        notify.  Mono-ns stamps convert to THIS process's wall clock at
        drain time (one anchor per drain; monotonic spacing preserved
        exactly)."""
        with self._lock:
            if not self._count:
                return []
            if self._count == self.capacity:
                recs = (self._ring[self._head:]
                        + self._ring[:self._head])
            else:
                start = (self._head - self._count) % self.capacity
                if start + self._count <= self.capacity:
                    recs = self._ring[start:start + self._count]
                else:
                    recs = (self._ring[start:]
                            + self._ring[:self._head])
            self._ring = [None] * self.capacity
            self._head = 0
            self._count = 0
        anchor_mono = clocks.mono_ns()
        anchor_wall = clocks.wall()
        out: List[dict] = []
        for t0, t1, cat, name, rid, args in recs:
            start_s = anchor_wall - (anchor_mono - t0) / 1e9
            rec = {
                "task_id": rid or b"",
                "name": name,
                "event": "SPAN",
                "cat": cat,
                "ts": start_s,
                "start_us": int(start_s * 1e6),
                "dur_us": max(0, (t1 - t0) // 1000),
                "worker_id": worker_id,
                "node_id": node_id,
                "job_id": b"",
            }
            if args:
                rec["args"] = args
            out.append(rec)
        return out

    def note_lost(self, n: int) -> None:
        """Count rows that were drained but never delivered (flush
        notify failed and the retry buffer overflowed): they fold into
        `dropped` so the exported counter and every flush's drop stamp
        keep the no-silent-caps contract even for flush-path loss."""
        if n > 0:
            with self._lock:
                self.dropped += n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            pending = self._count
        return {"recorded": self.recorded, "dropped": self.dropped,
                "sampled_out": self.sampled_out, "pending": pending}


_recorder: Optional[FlightRecorder] = None
_rec_lock = threading.Lock()


def recorder() -> FlightRecorder:
    """The per-process recorder, built from config on first use."""
    global _recorder
    if _recorder is None:
        with _rec_lock:
            if _recorder is None:
                _recorder = _from_config()
    return _recorder


def _from_config() -> FlightRecorder:
    try:
        from .config import get_config
        cfg = get_config()
        cats_s = cfg.flight_recorder_categories
        cats = (set(c.strip() for c in cats_s.split(",") if c.strip())
                if cats_s else None)
        return FlightRecorder(
            capacity=cfg.flight_recorder_capacity,
            categories=cats,
            sample_n=cfg.flight_recorder_sample_n,
            enabled=cfg.flight_recorder_enabled)
    except Exception:
        # The recorder must never take a daemon down with it.
        return FlightRecorder()


def reset() -> None:
    """Drop the singleton so the next recorder() re-reads config
    (tests; also correct after fork — each process records its own)."""
    global _recorder
    with _rec_lock:
        _recorder = None


def export_rows(labels: Dict[str, str]) -> List[dict]:
    """The unified-export rows EVERY process ships on its telemetry
    tick — RPC io_stats rollup, copy-audit totals, recorder counters —
    in the util.metrics snapshot row shape.  One definition so the
    agent and core-worker exports cannot silently diverge; callers
    append their daemon-specific gauges."""
    import time
    from . import rpc
    now = time.time()
    rec = recorder().stats()

    def row(name, value, help_="", lab=None):
        return {"name": name, "type": "counter", "help": help_,
                "ts": now, "labels": lab or labels,
                "value": float(value)}

    out = [
        row("ray_tpu_flight_recorder_recorded_total", rec["recorded"]),
        row("ray_tpu_flight_recorder_dropped_total", rec["dropped"],
            help_="flight-recorder records dropped (ring overwrite or "
                  "lost flush)"),
    ]
    for k, v in rpc.io_stats_snapshot().items():
        out.append(row(f"ray_tpu_io_{k}_total", v,
                       help_="process-wide RPC transport counters"))
    for tag, v in rpc.copy_audit_snapshot().items():
        out.append(row("ray_tpu_copied_bytes_total", v,
                       lab={**labels, "tag": tag},
                       help_="deliberate transfer-path copies "
                             "(copy audit)"))
    return out
