"""End-to-end deadline context for task execution.

The deadline a task was submitted with (``.options(timeout_s=...)``)
travels in its spec (protocol.make_task_spec "deadline", an absolute
``time.time()`` instant) and is installed here by the executing worker
for the duration of user code.  Nested ``.remote()`` calls and object
fetches made by that code then inherit the REMAINING budget instead of
starting a fresh clock — the composition rule that makes a chain of
hops fail together within the original budget (gRPC deadline
propagation; Dean & Barroso, "The Tail at Scale").

A contextvar (not a bare thread-local) so it follows async actor
methods across awaits; sync tasks set it inside the executor thread
(worker_main._run_sync), the same placement as tracing's execution
span, so contextvars never need to cross a run_in_executor boundary.
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional

_task_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "task_deadline", default=None)


def get() -> Optional[float]:
    """Absolute wall-clock deadline of the currently-executing task, or
    None when no deadline is in force."""
    return _task_deadline.get()


def remaining() -> Optional[float]:
    """Seconds of budget left, clamped at 0.0; None when no deadline."""
    d = _task_deadline.get()
    return None if d is None else max(0.0, d - time.time())


def expired() -> bool:
    d = _task_deadline.get()
    return d is not None and time.time() > d


def set_current(deadline: Optional[float]):
    """Install (returns a reset token for contextvars.reset)."""
    return _task_deadline.set(deadline)


def reset(token) -> None:
    _task_deadline.reset(token)
