"""Cluster scheduling policies: hybrid top-k, spread, label matching.

Reference surface: src/ray/raylet/scheduling/policy/ —
HybridSchedulingPolicy (hybrid_scheduling_policy.h:50: pack onto nodes
below a utilization threshold, choosing uniformly among the top-k to
avoid thundering herds; above the threshold fall back to spreading by
least utilization), SpreadSchedulingPolicy (round-robin over feasible
nodes), NodeLabelSchedulingPolicy (hard/soft label selectors), and the
scorer (scorer.h critical-resource utilization).

Shared by the GCS actor scheduler, the agents' spillback choice, and the
submitter-side lease routing (the reference's lease_policy.cc picks the
raylet BEFORE the request goes out the same way).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

# Reference defaults (ray_config_def.h scheduler_spread_threshold 0.5;
# top-k = 20% of nodes, RAY_scheduler_top_k_fraction).
SPREAD_THRESHOLD = 0.5
TOP_K_FRACTION = 0.2

# Suspicion score above which a node is deprioritized for NEW placement
# (gray-failure defense: alive-but-slow nodes cost more step time than
# dead ones).  Kept below the auto-drain threshold so placement steers
# away BEFORE evacuation kicks in.
SUSPECT_THRESHOLD = 0.5


def targetable(view: Dict) -> bool:
    """Whether a node (GCS NodeInfo.view dict) may receive NEW work.
    DRAINING nodes are alive — they finish in-flight leases and serve
    object pulls during migration — but the scheduler, spillback and
    submitter-side lease routing must all stop targeting them
    (reference: the raylet rejects leases while draining; autoscaler
    DrainNode semantics)."""
    return bool(view.get("alive")) and not view.get("draining")


def suspicion_of(view: Dict) -> float:
    """Gray-failure suspicion score of a node view (0 when absent)."""
    return float(view.get("suspicion") or 0.0)


def prefer_trusted(views, threshold: float = SUSPECT_THRESHOLD):
    """Deprioritize gray-suspect nodes: returns only the views below the
    suspicion threshold when any exist, else all views — a suspect node
    is a LAST resort, never a hard exclusion (a wrongly-suspected node
    must still be usable when it's the only feasible one).  Shared by
    the GCS scheduler, agent spillback, and submitter-side lease
    routing so every placement path steers the same way."""
    views = list(views)
    trusted = [v for v in views if suspicion_of(v) < threshold]
    return trusted or views


def feasible(avail: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in resources.items()
               if v > 0)


def critical_utilization(total: Dict[str, float],
                         avail: Dict[str, float],
                         resources: Dict[str, float]) -> float:
    """Utilization of the most-contended requested resource AFTER a
    hypothetical placement (reference: scorer.h — the max over dims is
    what drives both packing and spreading decisions)."""
    worst = 0.0
    dims = [k for k, v in resources.items() if v > 0] or list(total)
    for k in dims:
        t = total.get(k, 0.0)
        if t <= 0:
            continue
        used = t - avail.get(k, 0.0) + resources.get(k, 0.0)
        worst = max(worst, min(1.0, used / t))
    return worst


def hybrid_pick(candidates: Sequence[Tuple[object, Dict[str, float],
                                           Dict[str, float]]],
                resources: Dict[str, float],
                *, spread_threshold: float = SPREAD_THRESHOLD,
                top_k_fraction: float = TOP_K_FRACTION,
                rng: Optional[random.Random] = None):
    """candidates: (key, resources_total, resources_available) per node.
    Returns the chosen key or None.

    Phase 1 (pack): among feasible nodes whose post-placement critical
    utilization stays <= threshold, prefer the MOST utilized (binpack),
    picking uniformly from the top-k so concurrent schedulers don't
    herd onto one node.  Phase 2 (spread): otherwise take the least
    utilized feasible node."""
    rng = rng or random
    scored = [(key, critical_utilization(total, avail, resources))
              for key, total, avail in candidates
              if feasible(avail, resources)]
    if not scored:
        return None
    below = [(k, u) for k, u in scored if u <= spread_threshold]
    if below:
        below.sort(key=lambda ku: -ku[1])        # most utilized first
        k = max(1, int(len(below) * top_k_fraction))
        return rng.choice(below[:k])[0]
    return min(scored, key=lambda ku: ku[1])[0]


# Device-tier holders score ABOVE arena holders for the same bytes: an
# accelerator-resident arg skips the arena read AND the host->device
# upload, so a device copy is worth strictly more than a same-size
# plasma replica when the scheduler breaks locality ties.
DEVICE_TIER_WEIGHT = 2

# Storage-tier holders (spilled copy on local NVMe) score BELOW arena
# holders but above nothing at all: restoring from the local spill file
# (read_file_into, one pread into the arena) beats pulling the bytes
# over the wire from a peer's arena, but loses to bytes already mapped.
# The tier ladder the scheduler sees: device (2.0) > arena (1.0) >
# local NVMe (0.5) > remote (0).
DISK_TIER_WEIGHT = 0.5


def arg_locality(args) -> Dict[Tuple, int]:
    """Bytes-already-local map of a task spec's by-reference args:
    holder address -> total hinted bytes resident there.  Fed by the
    owner's replica directory (every holder counts, not just the
    primary) via the spec's location hints; inline args and refs
    without a size hint contribute nothing.  Device-tier holders (the
    spec's `dev` hint: nodes with the arrays accelerator-resident)
    count the same bytes at DEVICE_TIER_WEIGHT, so "already on this
    slice" outranks "in a peer's arena".  Storage-tier holders (the
    spec's `dsk` hint: nodes whose copy lives in a spill file) count at
    DISK_TIER_WEIGHT — and a holder appearing in BOTH the location list
    and the dsk hint (a spilled primary) counts ONCE at disk weight:
    its arena copy is gone, so full arena credit would overstate it."""
    out: Dict[Tuple, int] = {}
    for e in args or ():
        sz = int(e.get("sz") or 0) if isinstance(e, dict) else 0
        if sz <= 0 or "ref" not in e:
            continue
        disk = {tuple(a) for a in e.get("dsk") or ()}
        locs = e["ref"][2] if len(e["ref"]) > 2 else None
        if locs:
            first = locs[0]
            if not isinstance(first, (list, tuple)):  # legacy single addr
                locs = [locs]
            for a in locs:
                key = tuple(a)
                if key in disk:
                    continue          # scored once below, at disk weight
                out[key] = out.get(key, 0) + sz
        for key in disk:
            out[key] = out.get(key, 0) + int(sz * DISK_TIER_WEIGHT)
        for a in e.get("dev") or ():
            key = tuple(a)
            out[key] = out.get(key, 0) + sz * DEVICE_TIER_WEIGHT
    return out


def locality_bytes(loc_map: Dict[Tuple, int], addr) -> int:
    return loc_map.get(tuple(addr), 0) if loc_map else 0


def pick_by_locality(candidates, resources: Dict[str, float],
                     loc_map: Dict[Tuple, int],
                     min_bytes: int = 0):
    """Locality tiebreak WITHIN an already feasibility/label/trust-
    filtered candidate set: the feasible node holding the most hinted
    arg bytes (>= min_bytes), or None when locality has nothing to say
    — the caller then falls through to its normal policy, so locality
    can bias placement but never veto it.  candidates:
    (key, addr, resources_total, resources_available) per node."""
    if not loc_map:
        return None
    best, best_bytes = None, 0
    for key, addr, total, avail in candidates:
        if not feasible(avail, resources):
            continue
        b = locality_bytes(loc_map, addr)
        if b > best_bytes:
            best, best_bytes = key, b
    if best is None or best_bytes < max(min_bytes, 1):
        return None
    return best


def label_filter(candidates, selector: Optional[Dict[str, str]],
                 soft: Optional[Dict[str, str]] = None):
    """NodeLabelSchedulingPolicy: hard selector filters, soft selector
    reorders (preferred nodes first).  candidates: (key, labels)."""
    out = [(k, labels) for k, labels in candidates
           if not selector or all(labels.get(a) == b
                                  for a, b in selector.items())]
    if soft:
        out.sort(key=lambda kl: 0 if all(
            kl[1].get(a) == b for a, b in soft.items()) else 1)
    return [k for k, _ in out]
