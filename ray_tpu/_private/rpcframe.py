"""ctypes binding to the native RPC wire framer (src/rpcframe).

Same build discipline as the shm store (`_shmstore.so`): built on first
use with g++ (no pip deps), committed alongside the source so a
compiler-less host can still load it.  Unlike the store, the framer is
OPTIONAL: every entry point degrades gracefully — `available()` returns
False (after one warning) on a missing compiler, a corrupt `.so`, or an
ABI mismatch, and rpc.py then runs its byte-compatible pure-Python
framing.  A cluster may freely mix native and pure-Python nodes: the
wire format is identical (see docs/data_plane.md "Native framer").

Exposes three primitives consumed by rpc.Connection:

  Scanner      streaming msgpack boundary scanner: splits a stream chunk
               into CONTROL spans, RAW_BEGIN headers and RAW payload
               spans without building Python objects or resetting the
               decoder on raw headers.
  writev()     gather-write a list of buffers in one (looping) writev —
               a whole frame wave or raw header + arena views per
               syscall; stops at EAGAIN and reports how far it got.
  recv_into()  drain a socket directly into a destination buffer (the
               shm arena region of an in-flight pull) until the payload
               completes or the socket would block.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import List, Tuple

from . import native_build

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "rpcframe", "rpcframe.cc")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "_rpcframe.so")

ABI_VERSION = 1

# Event types (keep in sync with rpcframe.cc).
EV_CTRL = 0
EV_RAW_BEGIN = 1
EV_RAW_DATA = 2
EV_STASH_CTRL = 3

_build_lock = threading.Lock()
_lib = None
_failed = False


def ensure_built() -> str:
    """Build (or reuse) the shared object; raises only when there is
    neither a buildable toolchain NOR an existing artifact.  A
    compiler-less host whose checkout stamped the source newer than the
    committed .so keeps the committed binary (rf_abi_version() in
    _load() refuses a genuinely incompatible one)."""
    with _build_lock:
        return native_build.build_so(_SRC, _SO, fallback_to_stale=True)


def _load():
    global _lib, _failed
    if _lib is not None:
        return _lib
    if _failed:
        return None
    try:
        lib = ctypes.CDLL(ensure_built())
        lib.rf_abi_version.restype = ctypes.c_int
        if lib.rf_abi_version() != ABI_VERSION:
            raise OSError(
                f"_rpcframe.so ABI {lib.rf_abi_version()} != {ABI_VERSION}")
        lib.rf_scanner_new.restype = ctypes.c_void_p
        lib.rf_scanner_free.argtypes = [ctypes.c_void_p]
        lib.rf_scanner_reset.argtypes = [ctypes.c_void_p]
        lib.rf_scanner_set_raw_remaining.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64]
        lib.rf_scanner_raw_remaining.argtypes = [ctypes.c_void_p]
        lib.rf_scanner_raw_remaining.restype = ctypes.c_uint64
        lib.rf_scanner_spill_ptr.argtypes = [ctypes.c_void_p]
        lib.rf_scanner_spill_ptr.restype = ctypes.c_void_p
        lib.rf_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.rf_scan.restype = ctypes.c_int64
        lib.rf_writev.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
        lib.rf_writev.restype = ctypes.c_int64
        lib.rf_recv_into.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
        lib.rf_recv_into.restype = ctypes.c_int64
        _lib = lib
        return lib
    except Exception as e:  # noqa: BLE001 — any failure means fallback
        _failed = True
        logger.warning(
            "native RPC framer unavailable (%s: %s); falling back to the "
            "pure-Python framing (wire-compatible, slower bulk paths)",
            type(e).__name__, e)
        return None


def available() -> bool:
    return _load() is not None


def _reset_for_tests(so_path: str | None = None) -> None:
    """Drop the cached library/failure state (and optionally repoint the
    .so path) so fallback behavior is testable in-process."""
    global _lib, _failed, _SO
    _lib = None
    _failed = False
    if so_path is not None:
        _SO = so_path


# ---------------------------------------------------------------------------
# Buffer address extraction
# ---------------------------------------------------------------------------
def _addr_len(b) -> Tuple[int, int, object]:
    """(address, nbytes, keepalive) of a bytes-like object's payload.

    `bytes` — the dominant case: every frame in a wbuf wave — resolves
    allocation-free via a c_char_p cast (no ndarray per frame on the
    hot path this module exists to strip).  Arena memoryviews and other
    buffer exporters go through numpy; non-contiguous exotica are
    materialized (rare, small)."""
    if type(b) is bytes:
        addr = ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p).value
        return addr, len(b), b
    import numpy as np
    try:
        a = np.frombuffer(b, np.uint8)
    except (ValueError, TypeError):
        a = np.frombuffer(bytes(b), np.uint8)
    return a.ctypes.data, a.nbytes, a


def writev(fd: int, buffers: List, skip: int = 0):
    """Gather-write `buffers` (resuming `skip` bytes in) in as few
    writev syscalls as the socket accepts.  Returns
    (written, total, errno, nsyscalls): errno == 0 means success or a
    clean EAGAIN stop (written < total-skip); nonzero means a hard
    transport error."""
    lib = _load()
    n = len(buffers)
    ptrs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    keep = []
    total = 0
    for i, b in enumerate(buffers):
        addr, nb, ka = _addr_len(b)
        keep.append(ka)
        ptrs[i] = addr
        lens[i] = nb
        total += nb
    err = ctypes.c_int32()
    nsys = ctypes.c_int32()
    w = lib.rf_writev(fd, ptrs, lens, n, skip, ctypes.byref(err),
                      ctypes.byref(nsys))
    del keep
    return w, total, err.value, nsys.value


RECV_WOULD_BLOCK = 0
RECV_EOF = 1
RECV_ERROR = 2
RECV_FILLED = 3


def recv_into(fd: int, addr: int, cap: int):
    """Drain socket `fd` into raw memory at `addr` (≤ cap bytes).
    Returns (nread, state, errno, nsyscalls)."""
    lib = _load()
    state = ctypes.c_int32()
    err = ctypes.c_int32()
    nsys = ctypes.c_int32()
    got = lib.rf_recv_into(fd, addr, cap, ctypes.byref(state),
                           ctypes.byref(err), ctypes.byref(nsys))
    return got, state.value, err.value, nsys.value


class Scanner:
    """Per-connection streaming framer state (see module docstring).

    scan(data) -> (nevents, consumed); events are read from the .evt /
    .eva / .evb arrays.  nevents == -1 means a malformed stream (the
    caller aborts the connection, mirroring the Python framer)."""

    MAX_EVENTS = 128

    __slots__ = ("_lib", "_h", "evt", "eva", "evb", "_consumed",
                 "_spill_ptr")

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native framer unavailable")
        self._lib = lib
        self._h = lib.rf_scanner_new()
        if not self._h:
            raise MemoryError("rf_scanner_new failed")
        self.evt = (ctypes.c_int32 * self.MAX_EVENTS)()
        self.eva = (ctypes.c_int64 * self.MAX_EVENTS)()
        self.evb = (ctypes.c_int64 * self.MAX_EVENTS)()
        self._consumed = ctypes.c_uint64()
        self._spill_ptr = lib.rf_scanner_spill_ptr(self._h)

    def scan(self, data: bytes, offset: int = 0):
        """Scan `data[offset:]` (`data` must be bytes).  Returns
        (nevents, consumed).  Offset is applied by pointer arithmetic —
        no tail copy when a chunk needs several scan calls (dense
        raw-header streams exceed the event arrays)."""
        addr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
        n = self._lib.rf_scan(
            self._h, addr + offset, len(data) - offset, self.evt,
            self.eva, self.evb, self.MAX_EVENTS,
            ctypes.byref(self._consumed))
        return n, self._consumed.value

    def spill_bytes(self, off: int, length: int) -> bytes:
        """Stash bytes reclassified as control stream by the last scan
        (EV_STASH_CTRL events reference this buffer)."""
        return ctypes.string_at(self._spill_ptr + off, length)

    def set_raw_remaining(self, remaining: int) -> None:
        self._lib.rf_scanner_set_raw_remaining(self._h, remaining)

    def raw_remaining(self) -> int:
        return self._lib.rf_scanner_raw_remaining(self._h)

    def close(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.rf_scanner_free(h)

    def __del__(self):  # pragma: no cover — belt and braces
        try:
            self.close()
        except Exception:
            pass
