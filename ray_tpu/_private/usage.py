"""Usage telemetry, opt-out, no egress.

Reference: python/ray/_common/usage + _private/telemetry — collects
library usage + cluster shape per session and reports to a collector
endpoint unless RAY_USAGE_STATS_ENABLED=0.  This build has no network
egress by design, so the record lands in the GCS KV (`usage_stats` ns)
where operators can read it (`ray_tpu.usage_stats()`); the env toggle is
RAY_TPU_USAGE_STATS_ENABLED.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

KV_NS = "usage_stats"
_SCHEMA_VERSION = 1


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False")


def record_library_usage(library: str) -> None:
    """Called by library entry points (Train/Tune/Serve/Data/RLlib/LLM)."""
    if not enabled():
        return
    try:
        from .worker import global_runtime
        rt = global_runtime()
        if rt is None:
            return
        core = rt.core
        key = f"lib:{library}"
        core.gcs_call("kv_put", {"ns": KV_NS, "key": key,
                                 "value": b"1", "overwrite": True})
    except Exception:
        pass        # telemetry must never break user code


def record_session(core) -> None:
    if not enabled():
        return
    try:
        rec = {
            "schema_version": _SCHEMA_VERSION,
            "session_start": time.time(),
            "python": os.sys.version.split()[0],
            "mode": core.mode,
        }
        core.gcs_call("kv_put", {
            "ns": KV_NS, "key": f"session:{core.worker_id.hex()[:12]}",
            "value": json.dumps(rec).encode(), "overwrite": True})
    except Exception:
        pass


def usage_stats(core) -> dict:
    """Operator-facing read (reference: `ray usage-stats` surface)."""
    out = {"enabled": enabled(), "libraries": [], "sessions": []}
    try:
        keys = core.gcs_call("kv_keys", {"ns": KV_NS, "prefix": ""})
        for k in keys:
            k = k.decode() if isinstance(k, bytes) else k
            if k.startswith("lib:"):
                out["libraries"].append(k[4:])
            elif k.startswith("session:"):
                raw = core.gcs_call("kv_get", {"ns": KV_NS, "key": k})
                if raw:
                    out["sessions"].append(json.loads(bytes(raw)))
    except Exception:
        pass
    return out
