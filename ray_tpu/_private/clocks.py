"""Cluster clock plumbing: skew-injectable wall clock + NTP-style
offset estimation.

Every telemetry stamp in the runtime (task events, flight-recorder
spans, the agent's probe-reply timestamps) goes through `wall()` instead
of `time.time()`, for two reasons:

1. **Chaos injection.** The config knob `clock_skew_s` shifts this
   process's notion of wall time, so tests can build a cluster whose
   nodes disagree about "now" — the condition every real multi-host
   trace lives under — and assert the alignment machinery below actually
   repairs it.  Per-node `_system_config` reaches the agent's argv and
   the agent forwards it to its workers' env, so one `add_node(...,
   _system_config={"clock_skew_s": -5})` skews a whole node coherently
   (all processes on one host share the system clock; the skew model
   matches).

2. **One choke point.** When alignment eventually wants a disciplined
   clock (e.g. folding the GCS-estimated offset back into local stamps)
   there is exactly one function to teach.

Offset estimation is the classic NTP sample (reference: RFC 5905 §8;
Ray itself punts on this — its timeline mixes raw per-host clocks,
which is exactly the artifact PAPER.md's `ray timeline` shows at
scale): for a probe sent at t0 (local), received remotely at t1, echoed
at t2, and answered at t3 (local),

    theta = ((t1 - t0) + (t2 - t3)) / 2        # remote - local
    rtt   = (t3 - t0) - (t2 - t1)

theta's error is bounded by the path ASYMMETRY (|err| <= rtt/2), so the
estimator keeps a short window of samples and trusts the minimum-RTT
one — delay spikes inflate rtt, and the bound with it, while the
lowest-rtt sample of a window is the closest to symmetric the link
offered (same discipline as NTP's clock filter / Huygens' coded
probes).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional, Tuple

_skew: Optional[float] = None


def _resolve_skew() -> float:
    global _skew
    if _skew is None:
        try:
            from .config import get_config
            _skew = float(get_config().clock_skew_s)
        except Exception:
            _skew = 0.0
    return _skew


def reset_skew() -> None:
    """Re-read `clock_skew_s` from config on next use (tests)."""
    global _skew
    _skew = None


def wall() -> float:
    """This process's wall clock, including any injected chaos skew.
    All telemetry timestamps come from here so an injected skew shifts
    them coherently — exactly like a host whose clock is off."""
    s = _resolve_skew()
    return time.time() + s if s else time.time()


def mono_ns() -> int:
    """Monotonic nanoseconds — the flight recorder's stamp (immune to
    wall-clock steps; converted to wall time only at flush)."""
    return time.monotonic_ns()


def ntp_sample(t0: float, t1: float, t2: float,
               t3: float) -> Tuple[float, float]:
    """One NTP four-timestamp sample -> (theta, rtt).

    theta > 0 means the REMOTE clock is ahead of the local one; rtt is
    the round trip net of remote processing time.  |theta error| is
    bounded by rtt/2 (worst-case fully-asymmetric path)."""
    theta = ((t1 - t0) + (t2 - t3)) / 2.0
    rtt = max(0.0, (t3 - t0) - (t2 - t1))
    return theta, rtt


class OffsetEstimator:
    """Smoothed per-peer clock offset from repeated NTP samples.

    Keeps the last `window` samples and reports the theta of the
    minimum-RTT one (NTP clock-filter discipline), smoothed with a
    light EMA so a single lucky/unlucky probe can't step the estimate.
    `error_bound()` is the min-RTT/2 asymmetry bound — consumers that
    compare cross-node timestamps tighter than this are fooling
    themselves, and the docs say so."""

    def __init__(self, window: int = 8, alpha: float = 0.4):
        self._samples: deque = deque(maxlen=max(2, window))
        self._alpha = alpha
        self.offset: Optional[float] = None     # smoothed remote - local
        self.last_ts: float = 0.0               # monotonic of last add

    def add(self, t0: float, t1: float, t2: float, t3: float) -> float:
        theta, rtt = ntp_sample(t0, t1, t2, t3)
        self._samples.append((rtt, theta))
        best_theta = min(self._samples)[1]
        self.offset = best_theta if self.offset is None else (
            (1 - self._alpha) * self.offset + self._alpha * best_theta)
        self.last_ts = time.monotonic()
        return self.offset

    def error_bound(self) -> Optional[float]:
        """Half the best observed RTT: the asymmetry bound on `offset`."""
        if not self._samples:
            return None
        return min(self._samples)[0] / 2.0
