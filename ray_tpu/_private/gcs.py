"""Global Control Service: cluster membership, actor directory, KV, pubsub.

Control-plane equivalent of the reference's GCS server (reference:
src/ray/gcs/gcs_server.h:98 and the services in gcs_service.proto — JobInfo,
ActorInfo, NodeInfo, KV, PlacementGroup, WorkerInfo). One asyncio process on
the head node. Tables live in memory with an optional JSON-lines append log
for restart replay (the reference's Redis-backed store_client fills this role;
a file journal gives the same GCS-restart fault-tolerance story on one host).

Actor scheduling follows the reference's GcsActorScheduler: pick a node from
the live resource view, ask that node's agent to lease a worker and
instantiate the actor, publish lifecycle events on the actor channel.
"""

from __future__ import annotations

import argparse
import asyncio
import bisect
import json
import logging
import os
import struct
import time
from typing import Any, Dict, List, Optional

from . import clocks, diagnosis, loopmon, protocol, rpc
from . import scheduling_policy as policy
from .config import get_config

logger = logging.getLogger("ray_tpu.gcs")

_JLEN = struct.Struct("<I")

# KV namespaces excluded from the journal: high-churn ephemeral rendezvous
# state that is worthless after a restart.
_EPHEMERAL_NS = {"collective"}


class Journal:
    """Length-prefixed msgpack append log of GCS table mutations — the
    single-host stand-in for the reference's Redis-backed store_client
    (reference: gcs/store_client/redis_store_client.h; replay on restart
    per gcs_init_data.cc)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")
        try:
            self.size = os.path.getsize(path)
        except OSError:
            self.size = 0

    def append(self, kind: str, payload) -> None:
        data = rpc._pack([kind, payload])
        self._f.write(_JLEN.pack(len(data)) + data)
        self._f.flush()
        self.size += 4 + len(data)

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def read(path: str):
        out = []
        try:
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(4)
                    if len(hdr) < 4:
                        break
                    (n,) = _JLEN.unpack(hdr)
                    body = f.read(n)
                    if len(body) < n:
                        break    # torn tail write from a crash; ignore
                    out.append(rpc._unpack(body))
        except FileNotFoundError:
            pass
        return out


class JournalTailer:
    """Follow-mode reader of a live (possibly compacting) journal — the
    warm standby's replication stream.  Shared-path equivalent of a
    `journal_tail` streaming RPC: the primary's append+flush discipline
    makes every complete record visible to a same-host reader, and the
    length-prefix framing makes a half-flushed tail detectable (we
    simply retry it next poll, the same torn-tail tolerance
    Journal.read has).

    Compaction safety: the primary compacts by writing snapshot+suffix
    to a NEW file and atomically replacing the journal path.  A tailer
    mid-tail detects the replacement by inode change (or the file
    shrinking under its offset), reopens, and reports reset=True so the
    caller rebuilds its replica from the new file's start."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._ino = None
        self.offset = 0

    def _open(self) -> bool:
        try:
            f = open(self.path, "rb")
        except FileNotFoundError:
            return False
        if self._f is not None:
            self._f.close()
        self._f = f
        self._ino = os.fstat(f.fileno()).st_ino
        self.offset = 0
        return True

    def lag_bytes(self) -> int:
        """Bytes the primary has journaled that we have not yet applied."""
        try:
            return max(0, os.path.getsize(self.path) - self.offset)
        except OSError:
            return 0

    def poll(self):
        """-> (records, reset).  `records` are the complete records
        appended since the last poll; reset=True means the journal was
        replaced (compaction) and `records` restart from the NEW file's
        beginning — the caller must drop its replica tables first."""
        reset = False
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            return [], False
        if self._f is None:
            if not self._open():
                return [], False
        elif st.st_ino != self._ino or st.st_size < self.offset:
            if not self._open():
                return [], False
            reset = True
        out = []
        while True:
            self._f.seek(self.offset)
            hdr = self._f.read(4)
            if len(hdr) < 4:
                break
            (n,) = _JLEN.unpack(hdr)
            body = self._f.read(n)
            if len(body) < n:
                break               # torn tail: complete next poll
            try:
                out.append(rpc._unpack(body))
            except Exception:
                break               # half-flushed record: retry next poll
            self.offset += 4 + n
        return out, reset

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class NodeInfo:
    def __init__(self, node_id: bytes, address, resources: Dict[str, float],
                 labels: Dict[str, str], store_path: str, session_dir: str):
        self.node_id = node_id
        self.address = tuple(address)
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.labels = dict(labels)
        self.store_path = store_path
        self.session_dir = session_dir
        self.alive = True
        # Delta node views: the GCS's _view_epoch value at this node's
        # last SCHEDULING-RELEVANT change (registration, death/drain
        # transitions, resources_available movement, suspicion crossing
        # the trust threshold).  `get_nodes {"since": e}` returns only
        # views newer than e — heartbeats that change nothing no longer
        # make every polling client re-ship the full cluster view.
        self.view_version = 0
        self.last_heartbeat = time.monotonic()
        self.conn: Optional[rpc.Connection] = None  # GCS→agent client
        # {"reason", "deadline"} while the two-phase drain runs (NODE_DRAINING)
        self.draining: Optional[dict] = None
        self.drain_task: Optional[asyncio.Task] = None
        # Why this node was (last) drained — survives into DEAD so
        # observers can distinguish a gray-failure evacuation from a
        # planned preemption after the fact.
        self.drain_reason: Optional[str] = None
        # Gray-failure scoring state: RTT EMA from the GCS's own probe
        # pings, per-reporter peer observations about THIS node
        # (reporter node_id -> (rtt_s, monotonic ts)), and the resulting
        # suspicion score in [0, 1] (EMA'd; see _update_suspicion).
        self.rtt_ema: Optional[float] = None
        self.rtt_ts: float = 0.0        # monotonic of last probe sample
        # Clock alignment (NTP-style, fed by the same probes): smoothed
        # estimate of this node's wall clock MINUS the GCS's, min-RTT
        # filtered (see clocks.OffsetEstimator).  Stamped into node
        # views so timeline rendering can correct cross-node order, and
        # exported as the per-node skew gauge.
        self.clock = clocks.OffsetEstimator()
        # Runtime gauges off the agent's heartbeat (lease queue depth,
        # arena occupancy, ...): the CLI summary / dashboard node table
        # read them from the node view.
        self.runtime: Dict[str, float] = {}
        self.peer_rtts: Dict[bytes, tuple] = {}
        # reporter node_id -> (bytes_per_s, ts): peers' observed chunk
        # transfer rates FROM this node — the only signal that catches a
        # bandwidth-degraded (throttled/half-duplex-sick) link whose
        # small-frame ping RTT still looks healthy.
        self.peer_rates: Dict[bytes, tuple] = {}
        self.suspicion = 0.0
        self.suspect_since: Optional[float] = None
        # Data-plane transfer counters from the agent's heartbeat
        # (bytes_served / bytes_pulled): `ray_tpu list nodes` and the
        # dashboard's transfer column read them off the node view.
        # bulk_rate (B/s since the previous heartbeat) additionally
        # guards the gray auto-drain: a node mid-broadcast is BUSY, not
        # gray — its probe RTT inflates for exactly the duration of the
        # transfer (see _maybe_gray_drain).
        self.transfer: Dict[str, int] = {}
        self.bulk_rate: float = 0.0
        self._transfer_prev: int = 0
        self._transfer_prev_ts: float = 0.0
        # The agent's inbound connection (the one that called
        # register_node): its close is an immediate death signal for
        # cleanly crashed agents (see GcsServer._on_client_close).
        self.client_conn: Optional[rpc.Connection] = None

    @property
    def schedulable(self) -> bool:
        """May receive NEW work: alive and not draining."""
        return self.alive and self.draining is None

    def view(self) -> dict:
        return {
            "node_id": self.node_id,
            "address": list(self.address),
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "labels": self.labels,
            "store_path": self.store_path,
            # Attaching drivers adopt the node's session dir — it is
            # where resolve_gcs_address() finds the CURRENT advertised
            # GCS address, so a driver that joined pre-failover can
            # re-home instead of dialing the dead primary forever.
            "session_dir": self.session_dir,
            "alive": self.alive,
            "state": (protocol.NODE_DEAD if not self.alive
                      else protocol.NODE_DRAINING if self.draining
                      else protocol.NODE_ALIVE),
            "draining": ({"reason": self.draining["reason"]}
                         if self.alive and self.draining else None),
            "drain_reason": self.drain_reason,
            # Gray-failure observability: suspicion in [0,1] and the
            # last probe RTT EMA — surfaced in `ray_tpu list nodes`,
            # the dashboard node table, and consumed by every
            # placement path's prefer_trusted filter.
            "suspicion": round(self.suspicion, 3),
            # Authoritative deprioritization threshold, carried with the
            # score so consumers (dashboard) never hardcode a drifting
            # copy of scheduling_policy.SUSPECT_THRESHOLD.
            "suspect_threshold": policy.SUSPECT_THRESHOLD,
            "rtt_ms": (None if self.rtt_ema is None
                       else round(self.rtt_ema * 1000.0, 2)),
            "transfer": self.transfer,
            # Clock alignment: this node's wall clock minus the GCS's
            # (seconds; None until the first successful timestamped
            # probe), plus the asymmetry error bound — consumers
            # comparing cross-node stamps tighter than the bound are
            # reading noise.
            "clock_offset_s": (None if self.clock.offset is None
                               else round(self.clock.offset, 6)),
            "clock_err_bound_s": (
                None if self.clock.error_bound() is None
                else round(self.clock.error_bound(), 6)),
            "runtime": self.runtime,
        }


class ActorInfo:
    def __init__(self, actor_id: bytes, spec: dict):
        self.actor_id = actor_id
        self.spec = spec                     # creation spec (class key, args..)
        self.name = spec.get("name") or None
        self.state = protocol.ACTOR_PENDING
        self.address = None                  # worker RPC address when ALIVE
        self.node_id: Optional[bytes] = None
        self.restarts = 0
        self.max_restarts = spec.get("max_restarts", 0)
        self.death_cause: Optional[str] = None

    def view(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "name": self.name,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id,
            "restarts": self.restarts,
            "max_restarts": self.max_restarts,
            "death_cause": self.death_cause,
            "class_name": self.spec.get("class_name", ""),
        }


def _h_ping(conn, p):
    # Liveness ping; served shard-local under daemon_io_shards.
    return "pong"


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 journal_path: Optional[str] = None,
                 ha_dir: Optional[str] = None):
        self.host = host
        self.port = port
        self.journal_path = journal_path
        self.journal: Optional[Journal] = None
        # High availability (docs/control_plane.md §8): `ha_dir` is the
        # shared directory holding the advertised-address file and the
        # primary lease; None (the default, and every in-process test's
        # default) disables the lease machinery entirely.  The cluster
        # epoch is the fencing token: journaled, bumped exactly once per
        # failover by the promoted standby, stamped into registration
        # and heartbeat replies (and by agents into lease grants).
        self.ha_dir = ha_dir
        self.epoch = 1
        self._journal_epoch = 1     # epoch as last journaled/replayed
        self._replayed = False      # standby pre-replays before start()
        self._fenced = False
        self.fenced_event = asyncio.Event()
        self._failover_count = 0
        self._lease_task: Optional[asyncio.Task] = None
        self._last_snapshot_size = 0
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        self.nodes: Dict[bytes, NodeInfo] = {}
        self.actors: Dict[bytes, ActorInfo] = {}
        self.named_actors: Dict[str, bytes] = {}
        # Kills that arrived before the (background) registration did:
        # register_actor consumes these and buries the actor immediately.
        # Bounded: actor_id -> arrival time, pruned by TTL on insert.
        self._pending_kills: Dict[bytes, float] = {}
        self.jobs: Dict[bytes, dict] = {}
        self.placement_groups: Dict[bytes, dict] = {}
        self._pg_rr: Dict[bytes, int] = {}   # any-bundle rotation counters
        self._job_counter = 0
        self._subscribers: Dict[str, List[rpc.Connection]] = {}
        # Task-event sink (reference: gcs_task_manager.cc — bounded ring).
        from collections import deque as _deque
        from .config import get_config as _gc
        self.task_events: _deque = _deque(maxlen=_gc().gcs_task_events_max)
        # Opaque pre-packed event batches (count, blob): workers pack the
        # batch once, we store it without decoding (queries expand
        # lazily).  _te_blob_total tracks the event count for eviction.
        self._te_blobs: _deque = _deque()
        self._te_blob_total = 0
        self._te_blob_max = _gc().gcs_task_events_max
        # No silent caps: every event this sink evicts (ring overflow,
        # blob-budget eviction, undecodable blob) is counted, and
        # reporters' own buffer drops (the worker-side 10k deque)
        # accumulate per reporter — queries and /metrics surface the
        # totals so a truncated view is never presented as complete.
        self.task_events_dropped = 0
        self._reporter_drops: Dict[bytes, int] = {}
        # (name, labels_tuple) -> {"type", "value"/"sum"/"buckets", ...}
        self.metrics: Dict[tuple, dict] = {}
        self._metrics_reports = 0   # report count; drives reporter GC
        # Resource demand reported by core workers whose lease requests
        # came back infeasible (reference: autoscaler.proto resource
        # demand in GcsAutoscalerStateManager).  reporter -> shapes+ts.
        self.demand: Dict[bytes, dict] = {}
        # Created/removed wakeups for PG waiters (not journaled).
        self._pg_events: Dict[bytes, asyncio.Event] = {}
        # Bumped on every node registration; pending-actor scheduling resets
        # its deadline when this moves (new capacity may fit the actor).
        self._node_epoch = 0
        # Delta node views (see NodeInfo.view_version): monotonically
        # bumped on every scheduling-relevant view change.
        self._view_epoch = 0
        # alive-address -> NodeInfo index for heartbeat peer-stats
        # folding: rebuilt only when membership changes — building it
        # per heartbeat was O(N) x N heartbeats/tick = O(N^2) per tick
        # at fleet size.
        self._addr_index: Optional[Dict[str, NodeInfo]] = None
        self._closing = False
        # Daemon I/O sharding (config daemon_io_shards): accepted
        # connections live on shard event-loop threads; only `ping`
        # (pure I/O, no table access) is served shard-local — every
        # other handler mutates the tables and hops to this loop.
        self._io_shards = rpc.make_io_shard_pool("gcs")
        self._server = rpc.RpcServer(
            self._handlers(), name="gcs",
            on_client_close=self._on_client_close,
            io_shards=self._io_shards,
            # Same callable as the handlers dict: sharded and
            # single-loop mode must answer ping identically.
            shard_handlers={"ping": _h_ping})
        self._health_task: Optional[asyncio.Task] = None
        # Diagnosis plane: anomaly sink (detector firings reported by
        # every daemon/worker + the GCS's own watchdog), GCS-origin
        # anomaly counts for _self_metrics, and the black-box capture
        # manager (armed in start(); rate-limited per kind).
        self._anomalies: _deque = _deque(maxlen=256)
        self._anomaly_counts: Dict[str, int] = {}
        self._capture_mgr = None
        self._watchdog = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def _handlers(self):
        return {
            "kv_put": self.h_kv_put, "kv_get": self.h_kv_get,
            "kv_del": self.h_kv_del, "kv_keys": self.h_kv_keys,
            "kv_exists": self.h_kv_exists,
            "register_node": self.h_register_node,
            "get_nodes": self.h_get_nodes,
            "report_resources": self.h_report_resources,
            "drain_node": self.h_drain_node,
            "next_job_id": self.h_next_job_id,
            "register_job": self.h_register_job,
            "get_jobs": self.h_get_jobs,
            "register_actor": self.h_register_actor,
            "get_actor": self.h_get_actor,
            "list_actors": self.h_list_actors,
            "kill_actor": self.h_kill_actor,
            "actor_failed": self.h_actor_failed,
            "subscribe": self.h_subscribe,
            "publish": self.h_publish,
            "create_placement_group": self.h_create_placement_group,
            "remove_placement_group": self.h_remove_placement_group,
            "get_placement_group": self.h_get_placement_group,
            "list_placement_groups": self.h_list_placement_groups,
            "task_events": self.h_task_events,
            "get_task_events": self.h_get_task_events,
            "report_metrics": self.h_report_metrics,
            "get_metrics": self.h_get_metrics,
            "ping": _h_ping,
            "get_cluster_info": self.h_get_cluster_info,
            "report_demand": self.h_report_demand,
            "get_demand": self.h_get_demand,
            # Diagnosis plane: cluster-wide live introspection + anomaly
            # sink + black-box capture (docs/observability.md §5).
            "cluster_profile": self.h_cluster_profile,
            "report_anomaly": self.h_report_anomaly,
            "get_anomalies": self.h_get_anomalies,
            "capture": self.h_capture,
            # The GCS's OWN stacks/cpu_profile, same handler names every
            # other process serves (diagnosis.profile_handlers).
            **diagnosis.profile_handlers("gcs"),
        }

    def _mark_view_dirty(self, node: NodeInfo) -> None:
        """Record a scheduling-relevant change to `node`'s view so
        delta-polling clients (`get_nodes {"since": e}`) pick it up."""
        self._view_epoch += 1
        node.view_version = self._view_epoch

    def _alive_by_addr(self) -> Dict[str, NodeInfo]:
        idx = self._addr_index
        if idx is None:
            idx = self._addr_index = {
                f"{n.address[0]}:{n.address[1]}": n
                for n in self.nodes.values() if n.alive}
        return idx

    # ----------------------------------------------------------- telemetry --
    async def h_task_events(self, conn, p):
        # Reporter-side drop accounting: senders stamp their cumulative
        # buffer-overflow count ("dropped") and an id ("src"); the sink
        # keeps the latest per reporter so totals don't double-count.
        # Bounded against reporter churn by evicting the longest-silent
        # reporter (move-to-end on re-report): a bounded undercount of
        # long-dead reporters' drops, never unbounded memory.
        if p.get("src") is not None and p.get("dropped") is not None:
            d = self._reporter_drops
            d.pop(p["src"], None)
            d[p["src"]] = int(p["dropped"])
            while len(d) > 8192:
                d.pop(next(iter(d)))
        blob = p.get("blob")
        if blob is not None:
            # Opaque batch: one bin decode on the RPC frame instead of
            # thousands of per-event map decodes on the GCS loop.
            n = p.get("n", 0)
            self._te_blobs.append((n, blob))
            self._te_blob_total += n
            # Bound COMBINED retention (expanded ring + queued blobs) to
            # gcs_task_events_max — each side capped independently would
            # allow ~2x the documented limit after a query expands blobs.
            budget = max(self._te_blob_max - len(self.task_events), 0)
            while (self._te_blob_total > budget
                   and len(self._te_blobs) > 1):
                dn, _ = self._te_blobs.popleft()
                self._te_blob_total -= dn
                self.task_events_dropped += dn
            return True
        events = p["events"]
        overflow = (len(self.task_events) + len(events)
                    - (self.task_events.maxlen or 0))
        if overflow > 0:
            # deque(maxlen) evicts silently on extend; count it.
            self.task_events_dropped += min(overflow, len(events))
        self.task_events.extend(events)
        return True

    def _expanded_task_events(self):
        if self._te_blobs:
            # Expand accumulated blobs into the row ring (query-time cost;
            # queries are dashboard/state-API rate, not hot-path rate).
            blobs, self._te_blobs = list(self._te_blobs), type(self._te_blobs)()
            self._te_blob_total = 0
            for _n, blob in blobs:
                try:
                    rows = rpc._unpack(blob)
                except Exception:
                    # One corrupt blob (sender died mid-notify) must not
                    # fail the query or discard the healthy blobs.
                    logger.warning("dropping undecodable task-event blob "
                                   "(%d events)", _n)
                    self.task_events_dropped += _n
                    continue
                overflow = (len(self.task_events) + len(rows)
                            - (self.task_events.maxlen or 0))
                if overflow > 0:
                    self.task_events_dropped += min(overflow, len(rows))
                self.task_events.extend(rows)
        return self.task_events

    def _events_dropped_total(self) -> int:
        """Sink-side evictions plus every reporter's own buffer drops."""
        return self.task_events_dropped + sum(
            self._reporter_drops.values())

    async def h_get_task_events(self, conn, p):
        out = list(self._expanded_task_events())
        total = len(out)
        if p.get("job_id"):
            out = [e for e in out if e.get("job_id") == p["job_id"]]
        if p.get("task_id"):
            out = [e for e in out if e.get("task_id") == p["task_id"]]
        limit = p.get("limit", 10_000)
        clipped = max(0, len(out) - limit)
        out = out[-limit:]
        if p.get("with_meta"):
            # No silent caps: callers that ask get told how much of the
            # stream they are NOT seeing — events evicted before they
            # could be retained (sink ring + reporter buffers) and rows
            # clipped by this query's own limit.
            return {"events": out,
                    "dropped": self._events_dropped_total(),
                    "clipped": clipped,
                    "total_retained": total}
        return out

    async def h_report_metrics(self, conn, p):
        """Merge a per-process metric snapshot (reference: per-node
        metrics agents pushing to the head aggregator). Counters arrive as
        monotonic per-process totals keyed by worker, so aggregation sums
        the latest value per worker."""
        wid = p["worker_id"]
        # Recency is judged by GCS RECEIPT time (monotonic), never the
        # reporter's own wall stamp: a skewed host — the very condition
        # the clock-alignment feature exists for — must not have its
        # live metrics judged stale (or its dead ones judged fresh).
        recv = time.monotonic()
        for m in p["metrics"]:
            key = (m["name"], tuple(sorted(m.get("labels", {}).items())))
            entry = self.metrics.setdefault(key, {
                "name": m["name"], "labels": m.get("labels", {}),
                "type": m["type"], "help": m.get("help", ""),
                "per_worker": {}})
            entry["type"] = m["type"]
            entry["per_worker"][wid] = (m["value"], recv)
        # Periodic reporter eviction: worker processes churn (every dead
        # worker leaves its final snapshot behind), and with the unified
        # export EVERY process reports — without a sweep the per_worker
        # maps grow for the cluster's lifetime.  Stale gauge reporters
        # stop winning most-recent anyway; dropping their counter
        # contribution after 15min idle trades a bounded undercount for
        # bounded memory (the reference evicts dead-worker views the
        # same way).
        self._metrics_reports += 1
        if self._metrics_reports % 512 == 0:
            horizon = time.monotonic() - 900.0
            for entry in self.metrics.values():
                pw = entry["per_worker"]
                for w in [w for w, (_v, ts) in pw.items()
                          if ts < horizon]:
                    del pw[w]
            self.metrics = {k: e for k, e in self.metrics.items()
                            if e["per_worker"]}
        return True

    async def h_get_metrics(self, conn, p):
        out = []
        for entry in self.metrics.values():
            vals = list(entry["per_worker"].values())   # [(value, ts)]
            if entry["type"] == "gauge":
                # Most recently RECEIVED value wins (receipt monotonic,
                # skew-immune), not dict order.
                value = max(vals, key=lambda v: v[1])[0] if vals else 0.0
            elif entry["type"] == "histogram":
                value = {"count": sum(v[0]["count"] for v in vals),
                         "sum": sum(v[0]["sum"] for v in vals)}
                sets = [v[0] for v in vals
                        if v[0].get("buckets") and v[0].get("boundaries")]
                if sets and all(s["boundaries"] == sets[0]["boundaries"]
                                for s in sets):
                    value["boundaries"] = sets[0]["boundaries"]
                    value["buckets"] = [
                        sum(s["buckets"][i] for s in sets)
                        for i in range(len(sets[0]["buckets"]))]
            else:
                value = sum(v[0] for v in vals)
            out.append({"name": entry["name"], "labels": entry["labels"],
                        "type": entry["type"], "help": entry["help"],
                        "value": value})
        out.extend(self._self_metrics())
        return out

    def _self_metrics(self) -> List[dict]:
        """The GCS's own contribution to the unified export: per-node
        health/clock gauges derived from its tables, and the task-event
        sink's drop counter (the no-silent-caps satellite)."""
        out: List[dict] = [{
            "name": "ray_tpu_gcs_task_events_dropped_total",
            "labels": {}, "type": "counter",
            "help": "task events evicted by the GCS sink or dropped in "
                    "reporter buffers before reaching it",
            "value": float(self._events_dropped_total())}]
        # GCS HA: the fencing epoch and failover count, plus — when a
        # warm standby is tailing our journal — its replication lag
        # (read from the progress file the standby refreshes each poll).
        out.append({
            "name": "ray_tpu_gcs_epoch", "labels": {}, "type": "gauge",
            "help": "cluster epoch (fencing token): bumped exactly once "
                    "per GCS failover, stamped into every grant",
            "value": float(self.epoch)})
        out.append({
            "name": "ray_tpu_gcs_failover_total", "labels": {},
            "type": "counter",
            "help": "GCS failovers this instance participated in "
                    "(takeovers it performed or fencings it suffered)",
            "value": float(self._failover_count)})
        if self.ha_dir:
            sb = self._read_json(
                os.path.join(self.ha_dir, protocol.GCS_STANDBY_FILE))
            if sb and sb.get("ts"):
                out.append({
                    "name": "ray_tpu_gcs_standby_lag_bytes",
                    "labels": {}, "type": "gauge",
                    "help": "journal bytes the warm standby has not yet "
                            "applied to its hot replica tables",
                    "value": float(sb.get("lag_bytes") or 0)})
                out.append({
                    "name": "ray_tpu_gcs_standby_age_seconds",
                    "labels": {}, "type": "gauge",
                    "help": "seconds since the warm standby last "
                            "reported tail progress; grows without "
                            "bound when no standby is running",
                    "value": max(0.0, time.time() - float(sb["ts"]))})
        # Per-loop busy fractions (loopmon): single-core saturation of
        # the GCS main loop — or of any I/O shard — is a gauge, not an
        # inference from host CPU.  Stale entries stay visible with
        # their probe age: a wedged loop alarms instead of vanishing.
        for label, info in loopmon.snapshot_full().items():
            out.append({
                "name": "ray_tpu_daemon_loop_busy_ratio",
                "labels": {"daemon": "gcs", "loop": label},
                "type": "gauge",
                "help": "CPU-seconds per wall-second burned by the "
                        "thread running this event loop (1.0 = one "
                        "core saturated)",
                "value": info["ratio"]})
            out.append({
                "name": "ray_tpu_daemon_loop_stale_seconds",
                "labels": {"daemon": "gcs", "loop": label},
                "type": "gauge",
                "help": "age of this loop's last busy probe tick; "
                        "grows past the ~0.5s period when the loop "
                        "stops servicing callbacks",
                "value": info["stale_s"]})
        # GCS-origin detector firings (its own watchdog); every other
        # process exports its ray_tpu_anomaly_total through its own
        # registry snapshot, so totals never double-count.
        for kind, count in self._anomaly_counts.items():
            out.append({
                "name": "ray_tpu_anomaly_total",
                "labels": {"daemon": "gcs", "kind": kind, "node_id": ""},
                "type": "counter",
                "help": "hung-work detector firings by kind",
                "value": float(count)})
        st = self._server.shard_stats()
        if st["shards"]:
            out.append({
                "name": "ray_tpu_daemon_io_shard_hops_total",
                "labels": {"daemon": "gcs"}, "type": "counter",
                "help": "batched shard->main-loop crossings",
                "value": float(st["hops"])})
            out.append({
                "name": "ray_tpu_daemon_io_shard_requests_total",
                "labels": {"daemon": "gcs"}, "type": "counter",
                "help": "requests forwarded to the main loop by I/O "
                        "shards (requests/hops = wave batching factor)",
                "value": float(st["submitted"])})
        for node in self.nodes.values():
            if not node.alive:
                continue
            lab = {"node_id": node.node_id.hex()}
            if node.clock.offset is not None:
                out.append({
                    "name": "ray_tpu_node_clock_offset_seconds",
                    "labels": lab, "type": "gauge",
                    "help": "estimated node wall clock minus GCS wall "
                            "clock (NTP-style, min-RTT filtered)",
                    "value": node.clock.offset})
            if node.rtt_ema is not None:
                out.append({
                    "name": "ray_tpu_node_probe_rtt_seconds",
                    "labels": lab, "type": "gauge",
                    "help": "GCS health-probe RTT EMA",
                    "value": node.rtt_ema})
            out.append({
                "name": "ray_tpu_node_suspicion",
                "labels": lab, "type": "gauge",
                "help": "gray-failure suspicion score in [0, 1]",
                "value": node.suspicion})
        return out

    # ------------------------------------------------------- diagnosis --
    # (docs/observability.md §5: cluster-wide live introspection, the
    # anomaly sink, and anomaly-triggered black-box capture bundles.)

    async def h_cluster_profile(self, conn, p):
        """Cluster-wide stacks/CPU profile: fans out through every
        agent's node_profile (agent + its workers, concurrently) plus
        the GCS's own process, and stamps each node's clock offset so
        renderers can align cross-node samples.  Selectors: node_id
        (hex prefix), pid, job_id (hex prefix -> the nodes that job's
        tasks touched)."""
        kind = p.get("kind", "stacks")
        if kind not in ("stacks", "cpu_profile"):
            raise rpc.RpcError(f"unknown profile kind {kind!r}")
        return await self._cluster_profile(kind, p)

    async def _cluster_profile(self, kind: str, p: dict) -> dict:
        duration = float(p.get("duration_s", 2.0))
        interval = p.get("interval_s", 0.01)
        sel_node = p.get("node_id")
        sel_pid = p.get("pid")
        node_filter = None
        if p.get("job_id"):
            # Job selection is node-granular: workers are pooled across
            # jobs, so profile every node the job's task events touched.
            node_filter = {
                e["node_id"].hex()
                for e in self._expanded_task_events()
                if e.get("node_id")
                and e.get("job_id")
                and e["job_id"].hex().startswith(p["job_id"])}
        targets = []
        for node in self.nodes.values():
            if not node.alive or node.conn is None or node.conn.closed:
                continue
            hexid = node.node_id.hex()
            if sel_node and not hexid.startswith(str(sel_node)):
                continue
            if node_filter is not None and hexid not in node_filter:
                continue
            targets.append(node)
        payload = {"kind": kind, "duration_s": duration,
                   "interval_s": interval}
        if sel_pid is not None:
            payload["pid"] = int(sel_pid)

        async def _gcs_self():
            try:
                if kind == "stacks":
                    r = diagnosis.dump_stacks()
                else:
                    r = await diagnosis.cpu_profile(duration, interval)
                r["daemon"] = "gcs"
                return r
            except Exception as e:  # noqa: BLE001 — typed, not fatal
                return {"error": str(e)}

        async def _one_node(node):
            try:
                return node, await node.conn.call(
                    "node_profile", payload, timeout=duration + 30)
            except Exception as e:  # noqa: BLE001 — per-node error entry
                return node, {"error": str(e)}

        # The GCS isn't a node: include it unless a selector narrows
        # the sweep.  Everything samples CONCURRENTLY — one coherent
        # cluster-wide time window.
        include_gcs = not (sel_node or sel_pid or node_filter is not None)
        coros = [_one_node(n) for n in targets]
        if include_gcs:
            gcs_task = asyncio.ensure_future(_gcs_self())
        results = await asyncio.gather(*coros)
        out = {"kind": kind, "duration_s": duration,
               "ts": clocks.wall(), "nodes": {}}
        if include_gcs:
            out["gcs"] = await gcs_task
        for node, res in results:
            res = dict(res) if isinstance(res, dict) else {"error": str(res)}
            res["clock_offset_s"] = node.clock.offset
            res["clock_err_bound_s"] = node.clock.error_bound()
            out["nodes"][node.node_id.hex()] = res
        return out

    async def h_report_anomaly(self, conn, p):
        self._ingest_anomaly(dict(p))
        return True

    async def h_get_anomalies(self, conn, p):
        out = list(self._anomalies)
        if p.get("kind"):
            out = [a for a in out if a.get("kind") == p["kind"]]
        return out[-int(p.get("limit", 256)):]

    async def h_capture(self, conn, p):
        """Manual black-box capture (`ray_tpu capture`): same bundle as
        an anomaly trigger, force bypasses the per-kind rate limit."""
        kind = p.get("kind", "manual")
        path = await self._capture_bundle(
            kind, {"kind": kind, "daemon": "manual", "trigger": "rpc"},
            force=bool(p.get("force", True)))
        return {"captured": path is not None, "path": path,
                "suppressed": dict(self._capture_mgr.suppressed)
                if self._capture_mgr else {}}

    def _ingest_anomaly(self, info: dict) -> None:
        """Anomaly sink: every detector firing cluster-wide lands here
        (workers/agents via report_anomaly notifies, the GCS's own
        watchdog via the thread-safe callback).  Counted, published,
        overlaid on the timeline, and — rate-limited — captured."""
        info.setdefault("ts", time.time())
        self._anomalies.append(info)
        kind = info.get("kind", "unknown")
        if info.get("daemon") == "gcs":
            # Reporters export their own ray_tpu_anomaly_total through
            # their registry snapshots; the GCS has no registry export,
            # so its firings are counted here (see _self_metrics) —
            # counting reported ones too would double them.
            self._anomaly_counts[kind] = \
                self._anomaly_counts.get(kind, 0) + 1
            # ... and its recorder ring is never drained, so feed the
            # timeline sink directly (reported anomalies arrive as
            # recorder instants in the normal telemetry drains).
            wall = clocks.wall()
            self.task_events.append({
                "task_id": b"", "name": f"anomaly:{kind}",
                "event": "SPAN", "cat": "anomaly", "ts": wall,
                "start_us": int(wall * 1e6), "dur_us": 0,
                "worker_id": b"", "node_id": b"", "job_id": b"",
                "args": {k: v for k, v in info.items()
                         if k not in ("stack",) and
                         isinstance(v, (str, int, float, bool))}})
        self._publish("anomaly", {
            k: v for k, v in info.items() if k != "stack"})
        if get_config().anomaly_capture_enabled \
                and self._capture_mgr is not None:
            rpc.spawn(self._capture_bundle(kind, info))

    def _anomaly_from_thread(self, info: dict) -> None:
        loop = self._loop
        if loop is None:
            return
        try:
            loop.call_soon_threadsafe(self._ingest_anomaly, info)
        except RuntimeError:
            pass

    async def _capture_bundle(self, kind: str, info: dict,
                              force: bool = False) -> Optional[str]:
        """One `diag-<kind>-<ts>/` bundle: stacks + short CPU profile of
        the implicated nodes (all nodes if the anomaly names none),
        merged metrics, node views (suspicion/clock state included),
        the task-event/recorder ring, recent anomalies, and a manifest.
        Returns the bundle path, or None when rate-limited."""
        mgr = self._capture_mgr
        if mgr is None or not mgr.should_capture(kind, force=force):
            return None
        cfg = get_config()
        sel = {}
        if info.get("node_id"):
            sel["node_id"] = info["node_id"]
        try:
            stacks = await self._cluster_profile("stacks", dict(sel))
            prof = await self._cluster_profile(
                "cpu_profile",
                {**sel, "duration_s": cfg.diagnosis_capture_profile_s})
        except Exception as e:  # noqa: BLE001 — partial bundle > none
            stacks = prof = {"error": str(e)}
        parts = {
            "stacks": stacks,
            "cpu_profile": prof,
            "metrics": await self.h_get_metrics(None, {}),
            "nodes": [n.view() for n in self.nodes.values()],
            "recorder": list(self._expanded_task_events())[-2000:],
            "anomalies": list(self._anomalies),
        }
        try:
            path = mgr.write_bundle(kind, parts, manifest_extra=info)
        except OSError as e:
            logger.warning("diagnosis bundle write failed: %s", e)
            return None
        logger.warning("diagnosis: captured black-box bundle %s "
                       "(anomaly kind=%s)", path, kind)
        self._publish("anomaly", {"kind": kind, "capture_path": path})
        return path

    async def start(self):
        if self.journal_path and not self._replayed:
            self._replay(Journal.read(self.journal_path))
            self._replayed = True
        if self.journal_path:
            self.journal = Journal(self.journal_path)
            if self.epoch != self._journal_epoch:
                # Promoted standby: the epoch bump hits the journal
                # BEFORE the first request is served — a crash right
                # after this line still replays into the new epoch.
                self.journal.append("epoch", self.epoch)
                self.journal.sync()
                self._journal_epoch = self.epoch
        if self.ha_dir:
            os.makedirs(self.ha_dir, exist_ok=True)
            self._claim_lease()
        addr = await self._server.start_tcp(self.host, self.port)
        self.address = addr
        if self.ha_dir:
            # Advertise AFTER the socket listens: a client that re-reads
            # the address file must never be pointed at a closed port.
            self._write_json_atomic(
                os.path.join(self.ha_dir, protocol.GCS_ADDRESS_FILE),
                {"address": list(addr),
                 protocol.EPOCH_KEY: self.epoch,
                 "pid": os.getpid()})
            self._lease_task = asyncio.ensure_future(self._lease_loop())
        # Busy-fraction probe for the main loop (shards install their
        # own): saturation of the state-mutating loop becomes a gauge.
        loopmon.install("main")
        cfg = get_config()
        if cfg.diagnosis_enabled:
            self._loop = asyncio.get_running_loop()
            if cfg.anomaly_capture_enabled:
                root = cfg.diagnosis_capture_dir
                if not root:
                    import tempfile
                    base = (os.path.dirname(self.journal_path)
                            if self.journal_path else
                            os.path.join(tempfile.gettempdir(), "ray_tpu"))
                    root = os.path.join(base, "diagnosis")
                try:
                    os.makedirs(root, exist_ok=True)
                    self._capture_mgr = diagnosis.CaptureManager(
                        root,
                        min_interval_s=cfg.diagnosis_capture_min_interval_s,
                        max_bundles=cfg.diagnosis_capture_max_bundles)
                except OSError as e:
                    logger.warning("diagnosis capture disabled: %s", e)
            self._watchdog = diagnosis.Watchdog(
                daemon_name="gcs",
                detectors=[diagnosis.loop_wedge_detector()],
                notify=self._anomaly_from_thread,
                poll_s=cfg.diagnosis_poll_ms / 1000.0)
            self._watchdog.start()
        self._health_task = asyncio.ensure_future(self._health_loop())
        # Re-kick interrupted placement/scheduling loops (their coroutines
        # died with the previous process; agents re-register shortly).
        for pg in self.placement_groups.values():
            if pg["state"] == "PENDING":
                rpc.spawn(self._place_pg(pg))
        for actor in self.actors.values():
            if actor.state in (protocol.ACTOR_PENDING,
                               protocol.ACTOR_RESTARTING):
                rpc.spawn(self._reschedule_replayed(actor))
        logger.info("GCS listening on %s%s", addr,
                    " (journal replayed)" if self.journal else "")
        return addr

    def _log(self, kind: str, payload) -> None:
        if self.journal is not None:
            self.journal.append(kind, payload)
            limit = get_config().journal_snapshot_every_bytes
            # Compact at the threshold, but only once the log has also
            # doubled past the LAST snapshot: when live state alone
            # exceeds the threshold, an absolute trigger would rewrite
            # the full snapshot on every append.
            if limit and self.journal.size > max(
                    limit, 2 * self._last_snapshot_size):
                try:
                    self._compact_journal()
                except OSError as e:
                    logger.warning("journal compaction failed: %s", e)

    def _compact_journal(self) -> None:
        """Snapshot + truncate: serialize the journaled tables as the
        minimal record sequence into a fresh file and atomically replace
        the journal — replay afterwards is snapshot + suffix.  The
        replace is what a mid-tail standby detects by inode change."""
        old_size = self.journal.size
        tmp = self.journal_path + ".compact"
        try:
            os.unlink(tmp)          # a crashed attempt must not append
        except FileNotFoundError:
            pass
        snap = Journal(tmp)
        snap.append("snapshot", self._snapshot_records())
        snap.sync()
        snap.close()
        self.journal.close()
        os.replace(tmp, self.journal_path)
        self.journal = Journal(self.journal_path)
        self._last_snapshot_size = self.journal.size
        logger.info("journal compacted: %d -> %d bytes",
                    old_size, self.journal.size)

    def _snapshot_records(self) -> list:
        """Current journaled state as the record sequence that rebuilds
        it — `_replay` is the single decoder for both live journals and
        snapshots, so the two can never drift apart."""
        recs: list = [["epoch", self.epoch],
                      ["job_counter", self._job_counter]]
        for ns, d in self.kv.items():
            if ns in _EPHEMERAL_NS:
                continue
            for k, v in d.items():
                recs.append(["kv_put", {"ns": ns, "key": k, "value": v}])
        for job in self.jobs.values():
            recs.append(["job", job])
        for node in self.nodes.values():
            recs.append(["node", {
                "node_id": node.node_id, "address": list(node.address),
                "resources": node.resources_total, "labels": node.labels,
                "store_path": node.store_path,
                "session_dir": node.session_dir}])
        for actor in self.actors.values():
            recs.append(["actor_spec", {"actor_id": actor.actor_id,
                                        "spec": actor.spec}])
            recs.append(["actor_view", actor.view()])
        for pg in self.placement_groups.values():
            recs.append(["pg", pg])
        return recs

    def _reset_tables(self) -> None:
        """Drop every journaled table (snapshot replay, standby reset
        after a compaction landed mid-tail)."""
        self.kv = {}
        self.nodes = {}
        self.actors = {}
        self.named_actors = {}
        self.jobs = {}
        self.placement_groups = {}
        self._job_counter = 0
        self._addr_index = None

    def _log_actor(self, actor: ActorInfo, with_spec: bool = False) -> None:
        # Spec is immutable — journaled once at registration; transitions
        # journal only the (small) view.
        if with_spec:
            self._log("actor_spec", {"actor_id": actor.actor_id,
                                     "spec": actor.spec})
        self._log("actor_view", actor.view())

    def _replay(self, records) -> None:
        """Rebuild tables from the journal (reference: gcs_init_data.cc).
        Nodes replay as not-alive — live agents re-register over their
        reconnecting GCS connections within a heartbeat."""
        for kind, p in records:
            if kind == "kv_put":
                self.kv.setdefault(p["ns"], {})[p["key"]] = p["value"]
            elif kind == "kv_del":
                ns = self.kv.get(p["ns"], {})
                if p.get("prefix"):
                    for k in [k for k in ns if k.startswith(p["key"])]:
                        del ns[k]
                else:
                    ns.pop(p["key"], None)
            elif kind == "job_counter":
                self._job_counter = max(self._job_counter, p)
            elif kind == "job":
                self.jobs[p["job_id"]] = p
            elif kind == "node":
                node = NodeInfo(p["node_id"], p["address"], p["resources"],
                                p.get("labels", {}), p.get("store_path", ""),
                                p.get("session_dir", ""))
                node.alive = False
                self.nodes[node.node_id] = node
            elif kind == "actor_spec":
                if p["actor_id"] not in self.actors:
                    self.actors[p["actor_id"]] = ActorInfo(p["actor_id"],
                                                           p["spec"])
            elif kind == "actor_view":
                actor = self.actors.get(p["actor_id"])
                if actor is None:
                    continue    # spec record lost with a torn tail
                v = p
                actor.state = v["state"]
                actor.address = v["address"]
                actor.node_id = v["node_id"]
                actor.restarts = v["restarts"]
                actor.max_restarts = v["max_restarts"]  # kill() zeroes it
                actor.death_cause = v["death_cause"]
                if actor.name:
                    if actor.state != protocol.ACTOR_DEAD:
                        self.named_actors[actor.name] = actor.actor_id
                    elif self.named_actors.get(actor.name) == actor.actor_id:
                        del self.named_actors[actor.name]
            elif kind == "pg":
                self.placement_groups[p["pg_id"]] = p
            elif kind == "pg_del":
                self.placement_groups.pop(p, None)
            elif kind == "epoch":
                self.epoch = max(self.epoch, int(p))
                self._journal_epoch = self.epoch
            elif kind == "snapshot":
                # Compaction record: the tables reset and rebuild from
                # the embedded record sequence (then the journal suffix
                # after this record replays on top as usual).
                self._reset_tables()
                self._replay(p)

    async def _reschedule_replayed(self, actor: ActorInfo):
        ok = await self._schedule_actor(actor)
        if not ok:
            actor.state = protocol.ACTOR_DEAD
            actor.death_cause = ("scheduling failed after GCS restart: "
                                 "no feasible node")
            self._log_actor(actor)

    async def close(self):
        self._closing = True
        if self._health_task:
            self._health_task.cancel()
        if self._lease_task:
            self._lease_task.cancel()
        await self._server.close()
        if self._io_shards is not None:
            # After the server: bridged connection closes need the
            # shard loops alive to run.
            self._io_shards.close()

    # ------------------------------------------------- HA lease / fencing --
    # (docs/control_plane.md §8.)  The primary holds a disk lease under
    # ha_dir, renewed every ttl/3 — but ONLY while it can see fresh
    # heartbeats from a majority of its alive agents.  A primary
    # partitioned from the cluster therefore stops renewing and yields;
    # a standby partitioned from a HEALTHY primary never sees the lease
    # go stale (renewal rides the agents' votes, not the standby's view
    # of the primary), so it cannot steal the cluster: the split-brain
    # guard.  A fenced ex-primary (a higher epoch appears in the lease
    # file while it was frozen) refuses every write and exits.

    @staticmethod
    def _read_json(path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def _write_json_atomic(path: str, obj: dict) -> None:
        # pid-suffixed tmp: the promoted standby and a not-yet-fenced
        # ex-primary must never truncate each other's half-written tmp.
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True
        return True

    def _lease_path(self) -> str:
        return os.path.join(self.ha_dir, protocol.GCS_LEASE_FILE)

    def _claim_lease(self) -> None:
        """Take (or re-take) the primary lease at startup.  Refuses to
        start against a live holder: a fresh lease owned by a running
        pid means another primary is serving — starting anyway would be
        manufacturing the very split brain the lease exists to prevent."""
        ttl = float(get_config().gcs_lease_ttl_s)
        cur = self._read_json(self._lease_path())
        if cur:
            if int(cur.get("epoch", 0)) > self.epoch:
                raise RuntimeError(
                    f"GCS lease already held at epoch {cur.get('epoch')} "
                    f"> ours {self.epoch}: a newer primary exists")
            pid = int(cur.get("owner_pid") or 0)
            age = time.time() - float(cur.get("renewed", 0.0))
            if pid and pid != os.getpid() and self._pid_alive(pid) \
                    and age <= float(cur.get("ttl_s", ttl)):
                raise RuntimeError(
                    f"GCS lease held by live pid {pid} "
                    f"(age {age:.1f}s <= ttl): refusing to double-serve")
        self._renew_lease(ttl)

    def _renew_lease(self, ttl: float) -> None:
        self._write_json_atomic(self._lease_path(), {
            "epoch": self.epoch,
            "renewed": time.time(),
            "ttl_s": ttl,
            "owner_pid": os.getpid(),
            "address": list(getattr(self, "address",
                                    (self.host, self.port)))})

    def _heartbeat_majority_ok(self, fresh_window: float) -> bool:
        """Lease renewal votes: a majority of ALIVE agents must have
        heartbeated within the freshness window.  No agents (bootstrap,
        benches) trivially passes — there is no cluster to lose."""
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return True
        now = time.monotonic()
        fresh = sum(1 for n in alive
                    if now - n.last_heartbeat <= fresh_window)
        return fresh * 2 > len(alive)

    async def _lease_loop(self):
        cfg = get_config()
        ttl = float(cfg.gcs_lease_ttl_s)
        fresh_window = float(cfg.gcs_lease_heartbeat_fresh_s) or max(
            2.0, 4.0 * cfg.resource_report_period_ms / 1000.0)
        while not self._closing:
            try:
                cur = self._read_json(self._lease_path())
                if cur and int(cur.get("epoch", 0)) > self.epoch:
                    # We were frozen/partitioned long enough for the
                    # standby to take over: we are history.
                    self._fence(int(cur["epoch"]))
                    return
                if self._heartbeat_majority_ok(fresh_window):
                    self._renew_lease(ttl)
                else:
                    logger.warning(
                        "withholding GCS lease renewal: no fresh "
                        "heartbeat majority (partitioned from agents?)")
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("lease renewal pass failed")
            await asyncio.sleep(ttl / 3.0)

    def _fence(self, successor_epoch: int) -> None:
        """A successor bumped the epoch past ours: refuse every write
        from now on and signal the hosting process to exit (the
        subprocess main watches fenced_event; in-process tests assert on
        it directly)."""
        if self._fenced:
            return
        self._fenced = True
        self._failover_count += 1
        logger.error(
            "GCS FENCED: successor epoch %d > ours %d — refusing all "
            "writes and exiting", successor_epoch, self.epoch)
        self._ingest_anomaly({
            "kind": "gcs_fenced", "daemon": "gcs",
            "epoch": self.epoch, "successor_epoch": successor_epoch})
        self.fenced_event.set()

    def _check_writable(self, p: Optional[dict] = None) -> None:
        """Mutation fencing: a fenced ex-primary accepts no state
        mutation at all, and ANY primary rejects a mutation stamped
        with an epoch older than its own (a grant-holder acting on a
        pre-failover decision)."""
        if self._fenced:
            raise rpc.RpcError(
                f"stale_epoch: this GCS instance is fenced "
                f"(epoch {self.epoch})")
        if p:
            e = p.get(protocol.EPOCH_KEY)
            if e is not None and int(e) and int(e) < self.epoch:
                raise rpc.RpcError(
                    f"stale_epoch: mutation carries epoch {e} < "
                    f"current {self.epoch}")

    # ------------------------------------------------------------------ KV --
    async def h_kv_put(self, conn, p):
        self._check_writable(p)
        ns = self.kv.setdefault(p.get("ns", ""), {})
        key = p["key"]
        if not p.get("overwrite", True) and key in ns:
            return False
        ns[key] = p["value"]
        if p.get("ns", "") not in _EPHEMERAL_NS:
            self._log("kv_put", {"ns": p.get("ns", ""), "key": key,
                                 "value": p["value"]})
        return True

    async def h_kv_get(self, conn, p):
        return self.kv.get(p.get("ns", ""), {}).get(p["key"])

    async def h_kv_exists(self, conn, p):
        return p["key"] in self.kv.get(p.get("ns", ""), {})

    async def h_kv_del(self, conn, p):
        self._check_writable(p)
        ns = self.kv.get(p.get("ns", ""), {})
        prefix = p.get("prefix", False)
        if p.get("ns", "") not in _EPHEMERAL_NS:
            self._log("kv_del", {"ns": p.get("ns", ""), "key": p["key"],
                                 "prefix": prefix})
        if prefix:
            n = 0
            for k in [k for k in ns if k.startswith(p["key"])]:
                del ns[k]
                n += 1
            return n
        return 1 if ns.pop(p["key"], None) is not None else 0

    async def h_kv_keys(self, conn, p):
        ns = self.kv.get(p.get("ns", ""), {})
        pref = p.get("prefix") or ""
        return [k for k in ns if k.startswith(pref)]

    # ---------------------------------------------------------------- nodes --
    async def h_register_node(self, conn, p):
        self._check_writable(p)
        node = NodeInfo(p["node_id"], p["address"], p["resources"],
                        p.get("labels", {}), p.get("store_path", ""),
                        p.get("session_dir", ""))
        prev = self.nodes.get(node.node_id)
        if prev is not None:
            # Re-registration after a connection blip or GCS restart:
            # running leases still consume resources, so keep the last
            # reported availability (the next heartbeat refreshes it) and
            # retire the stale gcs->agent connection.
            node.resources_available = dict(prev.resources_available)
            if prev.conn is not None and not prev.conn.closed:
                await prev.conn.close()
        node.client_conn = conn
        self.nodes[node.node_id] = node
        self._node_epoch += 1
        self._addr_index = None
        self._mark_view_dirty(node)
        self._log("node", {
            "node_id": node.node_id, "address": list(node.address),
            "resources": node.resources_total, "labels": node.labels,
            "store_path": node.store_path,
            "session_dir": node.session_dir})
        rpc.spawn(self._connect_agent(node))
        self._publish(protocol.CH_NODE, {"event": "alive", "node": node.view()})
        if not p.get("view", True):
            # Registrants that don't consume the cluster view (agents,
            # the soak harness) skip the O(N) reply: a wave of N
            # registrations otherwise does O(N^2) view-building on this
            # loop, which is exactly the mass-(re)registration moment
            # the GCS can least afford it.
            return {"node_id": node.node_id, "num_nodes": len(self.nodes),
                    protocol.EPOCH_KEY: self.epoch}
        return {"cluster_nodes": [n.view() for n in self.nodes.values()],
                protocol.EPOCH_KEY: self.epoch}

    async def _connect_agent(self, node: NodeInfo):
        try:
            node.conn = await rpc.connect(node.address, name="gcs->agent")
        except rpc.ConnectionLost:
            logger.warning("cannot connect to agent %s", node.address)

    async def h_get_nodes(self, conn, p):
        """Full node views, or — with {"since": epoch} — only the views
        whose SCHEDULING-RELEVANT state changed after `epoch` (see
        _mark_view_dirty; pass since=-1 for a full delta-form bootstrap).
        Observability-only fields (runtime gauges, transfer counters,
        rtt/clock) do not dirty a view: dashboards and the CLI use the
        full form, scheduling clients (core_worker's 2s-cached view) use
        deltas so N pollers cost O(changes), not O(N) each."""
        since = (p or {}).get("since")
        if since is None:
            return [n.view() for n in self.nodes.values()]
        if since > self._view_epoch:
            since = -1      # GCS restarted with a fresh epoch: resend all
        return {"epoch": self._view_epoch,
                "changed": [n.view() for n in self.nodes.values()
                            if n.view_version > since],
                "total": len(self.nodes)}

    async def h_report_resources(self, conn, p):
        node = self.nodes.get(p["node_id"])
        if node is None or not node.alive:
            # The reporter was marked dead (health-check false positive —
            # e.g. a GC pause on the agent outlived the failure budget) or
            # predates a journal wipe.  Death is permanent for consumers
            # (its actors were restarted, its primaries written off), so
            # tell the agent its reports are going nowhere: it re-registers
            # under a FRESH node id and rejoins instead of zombieing.
            return False
        if node.resources_available != p["available"]:
            self._mark_view_dirty(node)
        node.resources_available = p["available"]
        node.last_heartbeat = time.monotonic()
        if p.get("runtime"):
            # Runtime gauges (lease queue depth, arena occupancy, ...):
            # straight into the node view for the CLI summary / dashboard;
            # the agent separately exports the same numbers as metrics.
            node.runtime = p["runtime"]
        if p.get("transfer"):
            node.transfer = p["transfer"]
            total = int(node.transfer.get("bytes_served") or 0) + \
                int(node.transfer.get("bytes_pulled") or 0)
            now_ts = time.monotonic()
            dt = now_ts - node._transfer_prev_ts
            if node._transfer_prev_ts and 0.0 < dt < 60.0:
                node.bulk_rate = max(0, total - node._transfer_prev) / dt
            node._transfer_prev = total
            node._transfer_prev_ts = now_ts
        peer_stats = p.get("peer_stats")
        if peer_stats:
            # Fold the reporter's per-peer link observations into each
            # TARGET node's evidence: multiple independent reporters
            # seeing high RTT to one node is the strongest gray signal
            # there is (differential observability).
            now = time.monotonic()
            by_addr = self._alive_by_addr()
            for addr_s, st in peer_stats.items():
                target = by_addr.get(addr_s)
                if target is None or target.node_id == p["node_id"]:
                    continue
                ts = now - float(st.get("age_s") or 0.0)
                rtt = st.get("rtt")
                if rtt is not None:
                    target.peer_rtts[p["node_id"]] = (float(rtt), ts)
                rate = st.get("rate")
                if rate is not None:
                    target.peer_rates[p["node_id"]] = (float(rate), ts)
        # Dict (truthy) keeps the legacy `ok is False` rejection check
        # working while carrying the cluster epoch: the heartbeat is how
        # every agent LEARNS a failover happened (and starts fencing
        # grants minted under the old epoch).
        return {"ok": True, protocol.EPOCH_KEY: self.epoch}

    async def h_drain_node(self, conn, p):
        """Two-phase graceful drain (reference: autoscaler.proto DrainNode;
        Pathways-style preemption handling — planned departure is distinct
        from abrupt death).  Phase 1 marks the node DRAINING: the scheduler
        and spillback stop targeting it, its ALIVE actors restart elsewhere
        through the normal restart path (before teardown, with a
        NodePreemptedError cause), and the agent migrates sole primary
        object copies to a peer.  Phase 2 — only at the deadline, or once
        the agent reports the drain complete — falls back to the hard-kill
        death path.  Payload: node_id, reason (preemption|idle|manual),
        deadline_s, wait (block until the node is dead)."""
        node = self.nodes.get(p["node_id"])
        if node is None:
            return False
        if not node.alive:
            return True          # already dead: drain is trivially done
        reason = p.get("reason") or protocol.DRAIN_MANUAL
        from .config import get_config
        d = p.get("deadline_s")   # explicit 0 = hard-kill now, not default
        deadline_s = float(get_config().node_drain_deadline_s
                           if d is None else d)
        if node.draining is None:
            node.draining = {"reason": reason,
                             "deadline": time.monotonic() + deadline_s}
            node.drain_reason = reason
            self._mark_view_dirty(node)     # schedulable flipped off
            logger.warning("node %s draining (reason=%s, deadline=%.1fs)",
                           node.node_id.hex()[:8], reason, deadline_s)
            self._publish(protocol.CH_NODE, {
                "event": "draining", "node": node.view(),
                "reason": reason, "deadline_s": deadline_s})
            node.drain_task = rpc.spawn(
                self._drain_node(node, reason, deadline_s))
        if p.get("wait") and node.drain_task is not None:
            try:
                await asyncio.wait_for(asyncio.shield(node.drain_task),
                                       deadline_s + 10.0)
            except asyncio.TimeoutError:
                return False
        return True

    async def _drain_node(self, node: NodeInfo, reason: str,
                          deadline_s: float):
        deadline = time.monotonic() + deadline_s
        cause = (f"NodePreemptedError: node {node.node_id.hex()[:8]} is "
                 f"being drained (reason={reason})")
        # Restart ALIVE actors elsewhere BEFORE teardown — _pick_node no
        # longer offers the draining node, so the existing restart path
        # lands them on a peer while the old incarnations keep serving
        # in-flight calls until the node exits.  Runs concurrently with
        # the agent-side object migration below; both are bounded by the
        # drain deadline (a restart that cannot place in time continues in
        # the background and is skipped by the final hard-kill pass, which
        # only death-handles actors still ALIVE on this node).
        pending = [rpc.spawn(self._handle_actor_death(actor, cause))
                   for actor in list(self.actors.values())
                   if actor.node_id == node.node_id
                   and actor.state == protocol.ACTOR_ALIVE]
        if node.conn is not None and not node.conn.closed:
            remaining = max(0.5, deadline - time.monotonic())
            pending.append(rpc.spawn(node.conn.call(
                "drain", {"reason": reason, "deadline_s": remaining},
                timeout=remaining + 5.0)))
        for t in pending:
            # Tasks may outlive the deadline wait below; retrieve their
            # exceptions (e.g. an agent drain RPC racing the node's death)
            # so they don't log as never-retrieved at GC.
            t.add_done_callback(lambda t: t.cancelled() or t.exception())
        if pending:
            # asyncio.wait, NOT wait_for(gather(...)): the latter CANCELS
            # the children at the deadline, which would kill an actor
            # restart mid create_actor_worker and strand the actor in
            # RESTARTING.  Slow restarts must keep running past the
            # deadline (the hard-kill pass below skips RESTARTING actors).
            await asyncio.wait(pending,
                               timeout=max(0.1, deadline - time.monotonic()))
        await self._mark_node_dead(node.node_id,
                                   f"drained (reason={reason})")
        # Graceful teardown: the agent SIGTERMs its workers (closing actor
        # connections so clients fail over to the restarted incarnations),
        # unlinks its shm arena and exits.
        live = self.nodes.get(node.node_id)
        if live is not None and live.conn is not None \
                and not live.conn.closed:
            try:
                live.conn.notify("shutdown", {"graceful": True})
            except rpc.ConnectionLost:
                pass

    async def h_report_demand(self, conn, p):
        """Core workers report unfulfilled lease shapes so the autoscaler
        can see cluster-wide pending demand (reference: autoscaler state
        aggregation in gcs_autoscaler_state_manager.cc)."""
        shapes = p.get("shapes") or []
        if shapes:
            self.demand[p["reporter"]] = {"shapes": shapes,
                                          "ts": time.monotonic()}
        else:
            self.demand.pop(p["reporter"], None)
        return True

    async def h_get_demand(self, conn, p):
        """Aggregate non-expired demand: task shapes from workers, plus
        pending actors and pending placement-group bundles."""
        ttl = p.get("ttl_s", 15.0)
        now = time.monotonic()
        shapes: list = []
        for reporter, entry in list(self.demand.items()):
            if now - entry["ts"] > ttl:
                del self.demand[reporter]
                continue
            shapes.extend(entry["shapes"])
        pending_actors = [
            a.spec.get("resources", {}) for a in self.actors.values()
            if a.state in (protocol.ACTOR_PENDING,
                           protocol.ACTOR_RESTARTING)]
        pending_bundles: list = []
        for pg in self.placement_groups.values():
            if pg["state"] == "PENDING":
                pending_bundles.append({"strategy": pg["strategy"],
                                        "bundles": pg["bundle_specs"]})
        return {"task_shapes": shapes,
                "pending_actors": [r for r in pending_actors if r],
                "pending_pgs": pending_bundles}

    async def _health_loop(self):
        """Active health checking (reference: gcs_health_check_manager.h —
        FailNode after `health_check_failure_threshold` missed periods),
        plus the gray-failure scorer: every period the GCS RTT-probes each
        agent, folds in peers' heartbeat-carried observations, and updates
        a per-node suspicion score.  Crash detection (silence) and gray
        detection (lateness) deliberately share this loop — a node can be
        sliding from one to the other."""
        from .config import get_config
        cfg = get_config()
        period = cfg.health_check_period_ms / 1000.0
        threshold = cfg.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            try:
                now = time.monotonic()
                for node in list(self.nodes.values()):
                    if node.alive and \
                            now - node.last_heartbeat > period * threshold:
                        await self._mark_node_dead(node.node_id,
                                                   "health check failed")
                for node in self.nodes.values():
                    if not node.alive:
                        continue
                    if node.conn is None or node.conn.closed:
                        # The probe dial is made once at registration
                        # and is not self-healing: re-dial here so a
                        # transient reset can't permanently blind
                        # probe-based gray detection (and rtt_ms
                        # observability) for a node that stays ALIVE
                        # on its own agent->gcs heartbeat dial.
                        rpc.spawn(self._redial_and_probe(
                            node, period * threshold))
                        continue
                    # Concurrent probes: a slow node must not delay
                    # the scoring (or probing) of its siblings.
                    rpc.spawn(self._probe_node(node,
                                               period * threshold))
                self._update_suspicion(cfg, period, threshold)
            except Exception:
                logger.exception("health check pass failed")

    async def _redial_and_probe(self, node: NodeInfo, bound: float) -> None:
        """Re-establish a dropped gcs→agent probe dial, then probe.  A
        refused dial while the node's heartbeats keep flowing is the
        asymmetric-partition signature — fold it in as the same
        worst-case sample a timed-out probe produces."""
        await self._connect_agent(node)
        if node.conn is None or node.conn.closed:
            rtt = max(bound, 1.0)
            node.rtt_ema = rtt if node.rtt_ema is None \
                else 0.7 * node.rtt_ema + 0.3 * rtt
            node.rtt_ts = time.monotonic()
            return
        await self._probe_node(node, bound)

    async def _probe_node(self, node: NodeInfo, bound: float) -> None:
        """One timed ping of an agent; folds the RTT into its EMA.  A
        failed/timed-out probe folds the full bound in as a worst-case
        sample rather than recording nothing: the documented
        asymmetric-partition case is exactly a GCS→node direction gone
        dark while node→GCS heartbeats keep flowing — silence THERE
        must raise suspicion (the EMA and the sustained window still
        require it to persist before anything drains).  Death from
        total silence stays the heartbeat detector's job.

        The same round trip doubles as the clock-alignment probe: the
        agent's ping reply carries its receive/transmit wall stamps
        (t1, t2), and with our own send/receive stamps (t0, t3) the
        NTP sample theta = ((t1-t0)+(t2-t3))/2 estimates that node's
        clock offset — min-RTT filtered and smoothed in
        node.clock (clocks.OffsetEstimator), exported via the node
        view and the per-node skew gauge, applied read-side by
        timeline rendering.  No extra RPC: measurement rides the
        health loop that already exists."""
        t0_mono = time.monotonic()
        t0 = clocks.wall()
        reply = None
        try:
            reply = await node.conn.call("ping", {},
                                         timeout=max(bound, 1.0))
            rtt = time.monotonic() - t0_mono
        except Exception:
            rtt = max(bound, 1.0)
        else:
            t3 = clocks.wall()
            if isinstance(reply, dict) and "t1" in reply \
                    and "t2" in reply:
                from .config import get_config as _gc
                if _gc().clock_align_enabled:
                    try:
                        node.clock.add(t0, float(reply["t1"]),
                                       float(reply["t2"]), t3)
                    except (TypeError, ValueError):
                        pass  # malformed stamps: RTT evidence still counts
        node.rtt_ema = rtt if node.rtt_ema is None \
            else 0.7 * node.rtt_ema + 0.3 * rtt
        node.rtt_ts = time.monotonic()

    def _update_suspicion(self, cfg, period: float, threshold: int) -> None:
        """Score each alive node against the cluster: suspicion rises
        when its probe/peer RTT exceeds both an absolute floor
        (gray_min_rtt_ms) and a multiple of its PEERS' median RTT
        (gray_rtt_ratio — shared load on the host running the GCS moves
        the median, not the ratio), or when its heartbeats arrive with
        gray-zone staleness.  Sustained suspicion past
        gray_suspicion_threshold auto-triggers the PR-3 two-phase drain
        with reason='gray' — closing detect -> avoid -> evacuate."""
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return
        now = time.monotonic()
        # Same 30s freshness bar as each node's own obs below: a frozen
        # EMA (probe conn died) must not keep skewing the cluster
        # baseline its PEERS are measured against.
        rtt_pairs = [(n, n.rtt_ema) for n in alive
                     if n.rtt_ema is not None and now - n.rtt_ts < 30.0]
        susp_threshold = float(cfg.gray_suspicion_threshold)
        min_rtt = float(cfg.gray_min_rtt_ms) / 1000.0
        ratio = float(cfg.gray_rtt_ratio)
        report_s = cfg.resource_report_period_ms / 1000.0
        death_bound = period * threshold

        # Evict long-stale peer evidence: reporters die and re-register
        # under fresh node ids forever (PR-3 rejoin path), so without a
        # sweep these dicts grow monotonically for the cluster's
        # lifetime (the agent-side twin, _peer_stats, evicts at the
        # same horizon for the same reason).
        for node in alive:
            for d in (node.peer_rtts, node.peer_rates):
                for rid in [r for r, (_v, ts) in d.items()
                            if now - ts > 900.0]:
                    del d[rid]

        def _fresh_vals(d):
            """Fresh (<30s) values from healthy reporters only: a gray
            reporter measures every peer through its own sick link."""
            return sorted(
                v for rid, (v, ts) in d.items()
                if now - ts < 30.0 and v is not None
                and getattr(self.nodes.get(rid), "suspicion",
                            1.0) < susp_threshold)

        # Per-node peer-observed transfer rate (upper median — the
        # healthier read; rate has no own-probe to exonerate a node, so
        # a lone reporter never counts).
        rate_med = {}
        for node in alive:
            rates = _fresh_vals(node.peer_rates)
            if len(rates) >= 2:
                rate_med[node.node_id] = rates[len(rates) // 2]

        def _loo_median(sorted_vals, own, lower=False):
            """Median of sorted_vals with ONE occurrence of `own`
            removed (leave-one-out; own=None removes nothing).  Sorting
            once and bisecting here keeps the tick O(N log N) — a
            per-node re-sort is O(N^2 log N) of event-loop stall at
            fleet size, and a slow scorer tick would feed back into its
            own heartbeat-staleness evidence."""
            n = len(sorted_vals)
            if own is None:
                if not n:
                    return None
                return sorted_vals[(n - 1) // 2 if lower else n // 2]
            if n <= 1:
                return None
            m = (n - 2) // 2 if lower else (n - 1) // 2
            if m >= bisect.bisect_left(sorted_vals, own):
                m += 1
            return sorted_vals[m]

        own_rtt = {m.node_id: r for m, r in rtt_pairs}
        rtts_sorted = sorted(own_rtt.values())
        rates_sorted = sorted(rate_med.values())

        for node in alive:
            # Baseline = median RTT of the OTHER nodes: including a
            # node's own RTT in its baseline lets the slow node of a
            # 2-node cluster (or the slow half of any even one) set its
            # own floor and never look suspect.
            baseline = _loo_median(rtts_sorted,
                                   own_rtt.get(node.node_id))
            # Stale probe evidence is no evidence: if the gcs->agent
            # probe conn died, rtt_ema freezes at its last value — a
            # node that was briefly slow must not stay suspect forever
            # on a frozen reading (peer_rtts below age out the same way).
            obs = node.rtt_ema if now - node.rtt_ts < 30.0 else None
            fresh = _fresh_vals(node.peer_rtts)
            # Lower median across reporters, and never a LONE reporter
            # overriding a fresh healthy probe: a genuinely slow node
            # looks slow to every reporter, so the lower median stays
            # high — but one accuser (flaky, or itself sub-threshold
            # gray) can't defame a node the GCS's own probe exonerates.
            if fresh and (obs is None or len(fresh) >= 2):
                med = fresh[(len(fresh) - 1) // 2]
                obs = med if obs is None else max(obs, med)
            raw = 0.0
            if obs is not None and baseline is not None:
                floor = max(min_rtt, ratio * baseline)
                if obs > floor:
                    raw = min(1.0, (obs - floor) / floor)
            # Bandwidth deficit: peers pull from this node at least
            # gray_rtt_ratio slower than from the rest of the cluster —
            # the one signal a throttled/half-duplex-sick link shows
            # while its small-frame ping RTT still looks clean.
            rm = rate_med.get(node.node_id)
            base_r = _loo_median(rates_sorted, rm, lower=True) \
                if rm is not None else None
            if rm is not None and base_r is not None:
                if rm * ratio < base_r:
                    raw = max(raw, min(1.0, (base_r / max(rm, 1.0)
                                             - ratio) / ratio))
            hb_age = now - node.last_heartbeat
            if hb_age > max(3.0 * report_s, 1.0):
                # Heartbeats late but not yet fatal: the gray zone
                # between healthy and the crash detector's verdict.
                raw = max(raw, min(1.0, hb_age / death_bound))
            was_suspect = node.suspicion >= policy.SUSPECT_THRESHOLD
            node.suspicion = 0.7 * node.suspicion + 0.3 * raw
            if (node.suspicion >= policy.SUSPECT_THRESHOLD) != was_suspect:
                # Trust-tier flip is what delta-view consumers
                # (prefer_trusted) act on; sub-threshold EMA drift is not.
                self._mark_view_dirty(node)
            if node.suspicion >= susp_threshold:
                if node.suspect_since is None:
                    node.suspect_since = now
                    logger.warning(
                        "node %s gray-suspect: suspicion=%.2f "
                        "(rtt=%s, baseline=%s, hb_age=%.2fs)",
                        node.node_id.hex()[:8], node.suspicion,
                        f"{obs * 1000:.0f}ms" if obs else "n/a",
                        f"{baseline * 1000:.1f}ms" if baseline else "n/a",
                        hb_age)
                self._maybe_gray_drain(node, alive, now,
                                       float(cfg.gray_sustained_s),
                                       bool(cfg.gray_auto_drain),
                                       susp_threshold)
            elif node.suspicion < 0.8 * susp_threshold:
                node.suspect_since = None       # hysteresis

    def _maybe_gray_drain(self, node: NodeInfo, alive, now: float,
                          sustained_s: float, auto: bool,
                          susp_threshold: float) -> None:
        if not auto or node.draining is not None or not node.alive:
            return
        if node.suspect_since is None \
                or now - node.suspect_since < sustained_s:
            return
        from .config import get_config as _gc
        exempt = float(_gc().gray_bulk_drain_exempt_bytes_per_s)
        if exempt > 0 and node.bulk_rate >= exempt:
            # Mid-broadcast/bulk-serving node: its probe RTT inflates for
            # exactly as long as the transfer runs (PR 4's "bulk transfer
            # != RTT" principle, applied to the GCS's own probes, which
            # queue behind the agent's chunk serving).  Placement already
            # deprioritizes it via suspicion; EVACUATING it would kill
            # the very transfer that made it look slow.  The auto-drain
            # resumes the first sustained-suspect window after the bulk
            # flow stops.
            node.suspect_since = now
            return
        # Never evacuate INTO nothing: require at least one other
        # schedulable, non-suspect node to receive the work — if the
        # whole cluster looks gray, the problem is the observer (or the
        # fabric), not this node.
        others = [m for m in alive
                  if m is not node and m.schedulable
                  and m.suspicion < susp_threshold]
        if not others:
            return
        logger.warning(
            "auto-draining gray node %s (suspicion %.2f sustained %.1fs)",
            node.node_id.hex()[:8], node.suspicion,
            now - node.suspect_since)
        rpc.spawn(self.h_drain_node(None, {
            "node_id": node.node_id, "reason": protocol.DRAIN_GRAY}))

    def _on_client_close(self, conn):
        """A registered agent's inbound connection closed: for a crashed
        or SIGKILL'd agent the kernel closes the socket immediately, so
        mark the node dead NOW instead of waiting health_check_period_ms ×
        health_check_failure_threshold.  Netsplits send no FIN/RST and
        still take the heartbeat-timeout path; an agent-side reconnect
        re-registers (fresh NodeInfo), so a stale conn's close can never
        kill the successor (identity check below)."""
        if self._closing:
            return
        for node in self.nodes.values():
            if node.client_conn is conn and node.alive:
                rpc.spawn(self._mark_node_dead(
                    node.node_id, "agent connection closed"))
                break

    async def _mark_node_dead(self, node_id: bytes, reason: str):
        node = self.nodes.get(node_id)
        if not node or not node.alive:
            return
        node.alive = False
        node.draining = None
        self._addr_index = None
        self._mark_view_dirty(node)
        logger.warning("node %s dead: %s", node_id.hex()[:8], reason)
        self._publish(protocol.CH_NODE, {"event": "dead", "node": node.view(),
                                         "reason": reason})
        # Fail actors on that node; restart if allowed.
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state == protocol.ACTOR_ALIVE:
                await self._handle_actor_death(actor, f"node died: {reason}")

    # --------------------------------------------------------------- pubsub --
    async def h_subscribe(self, conn, p):
        # Idempotent per (channel, conn): a client whose subscribe RPC
        # raced a GCS restart retries it AFTER its reconnect hook already
        # re-subscribed — appending blindly would double every notify.
        subs = self._subscribers.setdefault(p["channel"], [])
        if conn not in subs:
            subs.append(conn)
        return True

    async def h_publish(self, conn, p):
        self._publish(p["channel"], p["message"])
        return True

    def _publish(self, channel: str, message):
        subs = self._subscribers.get(channel, [])
        dead = []
        for c in subs:
            if c.closed:
                dead.append(c)
                continue
            try:
                c.notify("pubsub", {"channel": channel, "message": message})
            except rpc.ConnectionLost:
                dead.append(c)
        for c in dead:
            subs.remove(c)

    # ----------------------------------------------------------------- jobs --
    async def h_next_job_id(self, conn, p):
        self._check_writable(p)
        self._job_counter += 1
        self._log("job_counter", self._job_counter)
        return self._job_counter

    async def h_register_job(self, conn, p):
        self._check_writable(p)
        self.jobs[p["job_id"]] = {"job_id": p["job_id"],
                                  "driver_addr": p.get("driver_addr"),
                                  "start_time": time.time(), "alive": True}
        self._log("job", self.jobs[p["job_id"]])
        return True

    async def h_get_jobs(self, conn, p):
        return list(self.jobs.values())

    # --------------------------------------------------------------- actors --
    async def h_register_actor(self, conn, p):
        """Register + schedule an actor (reference: gcs_actor_manager.cc
        RegisterActor/CreateActor; scheduling in gcs_actor_scheduler.cc)."""
        self._check_writable(p)
        spec = p["spec"]
        actor_id = spec["actor_id"]
        name = spec.get("name")
        if name:
            existing_id = self.named_actors.get(name)
            if existing_id is not None and existing_id != actor_id:
                existing = self.actors.get(existing_id)
                if existing and existing.state != protocol.ACTOR_DEAD:
                    if spec.get("get_if_exists"):
                        return {"existing": True, "actor": existing.view()}
                    raise ValueError(f"actor name {name!r} already taken")
        existing = self.actors.get(actor_id)
        if existing is not None:
            # Retried register (e.g. driver reconnected after a GCS
            # restart that already replayed this actor) — idempotent.
            return {"existing": True, "actor": existing.view()}
        actor = ActorInfo(actor_id, spec)
        self.actors[actor_id] = actor
        if name:
            self.named_actors[name] = actor_id
        self._log_actor(actor, with_spec=True)
        if actor_id in self._pending_kills:
            self._pending_kills.pop(actor_id, None)
            actor.max_restarts = 0
            actor.state = protocol.ACTOR_DEAD
            actor.death_cause = "killed before registration completed"
            self._log_actor(actor)
            return {"existing": False, "actor": actor.view()}
        # Placement runs in the background: RegisterActor replies once the
        # actor is recorded, the creation task proceeds asynchronously
        # (reference: gcs_actor_manager.cc RegisterActor vs CreateActor —
        # clients poll/get with wait_alive).  Keeping PENDING visible also
        # lets the autoscaler see the actor as demand and bring capacity
        # before the scheduling deadline.  Fast placements (warm worker
        # pool) get a short grace so the common path replies ALIVE with
        # the address inline — first-call latency matters.
        rpc.spawn(self._schedule_or_bury(actor))
        start = time.monotonic()
        while actor.state == protocol.ACTOR_PENDING \
                and time.monotonic() - start < 0.4:
            await asyncio.sleep(
                0.01 if time.monotonic() - start < 0.2 else 0.05)
        return {"existing": False, "actor": actor.view()}

    async def _schedule_or_bury(self, actor: ActorInfo):
        ok = await self._schedule_actor(actor)
        if not ok and actor.state == protocol.ACTOR_PENDING:
            actor.state = protocol.ACTOR_DEAD
            # Keep a more specific cause if the scheduler recorded one
            # (e.g. a runtime-env install failure).
            actor.death_cause = (actor.death_cause
                                 or "scheduling failed: no feasible node")
            if actor.name and \
                    self.named_actors.get(actor.name) == actor.actor_id:
                del self.named_actors[actor.name]
            self._log_actor(actor)
            self._publish(protocol.CH_ACTOR,
                          {"event": "dead", "actor": actor.view()})

    def _pick_node(self, resources: Dict[str, float],
                   strategy: Optional[dict],
                   locality: Optional[Dict] = None) -> Optional[NodeInfo]:
        """Feasibility + best-fit over the live resource view. Honors
        node-affinity and placement-group strategies; falls back to
        most-available (spread-ish, mirroring hybrid policy's behavior
        below the packing threshold).

        `locality` (addr -> hinted arg bytes, from the spec's replica-
        directory hints) biases the DEFAULT policy toward nodes already
        holding the bytes — strictly below feasibility, explicit
        strategies, labels, and trusted-first ordering."""
        if strategy and strategy.get("type") == "node_affinity":
            node = self.nodes.get(strategy["node_id"])
            if node and node.schedulable:
                return node
            if not strategy.get("soft"):
                return None
        if strategy and strategy.get("type") == "placement_group":
            pg = self.placement_groups.get(strategy["pg_id"])
            if pg and pg["state"] == "CREATED":
                idx = strategy.get("bundle_index", 0)
                if idx < 0:
                    # any-bundle: rotate across live bundle nodes so retries
                    # reach a bundle with room (the GCS does not track
                    # per-bundle usage; agents reject exhausted bundles)
                    live = [b for b in pg["bundles"]
                            if (n := self.nodes.get(b["node_id"]))
                            and n.schedulable]
                    if not live:
                        return None
                    self._pg_rr[pg["pg_id"]] = (
                        self._pg_rr.get(pg["pg_id"], -1) + 1)
                    b = live[self._pg_rr[pg["pg_id"]] % len(live)]
                    return self.nodes[b["node_id"]]
                bundle = pg["bundles"][idx]
                node = self.nodes.get(bundle["node_id"])
                if node and node.schedulable:
                    return node
            return None
        live = [n for n in self.nodes.values() if n.schedulable]
        if strategy and strategy.get("type") == "node_label":
            keep = set(policy.label_filter(
                [(n.node_id, n.labels or {}) for n in live],
                strategy.get("hard") or None))
            live = [n for n in live if n.node_id in keep]
            if not live:
                return None
            soft = strategy.get("soft")
            if soft:
                # Soft preference: place within the preferred subset when
                # any of it is feasible, falling back to all hard matches
                # (reference: node_label policy's soft reordering).
                preferred = [n for n in live
                             if all((n.labels or {}).get(a) == b
                                    for a, b in soft.items())]
                if preferred:
                    pick = policy.hybrid_pick(
                        [(n, n.resources_total, n.resources_available)
                         for n in preferred], resources)
                    if pick is not None:
                        return pick
        def _pick(cand_nodes):
            cands = [(n, n.resources_total, n.resources_available)
                     for n in cand_nodes]
            if strategy and strategy.get("type") == "spread":
                # Least-utilized feasible node (reference:
                # spread_scheduling_policy.h round-robins; least-utilized
                # is the stateless equivalent under a live resource view).
                feas = [(n, policy.critical_utilization(t, a, resources))
                        for n, t, a in cands
                        if policy.feasible(a, resources)]
                return min(feas, key=lambda nu: nu[1])[0] if feas else None
            if locality:
                # Bytes-already-local tiebreak (within this trust tier;
                # feasibility checked inside): a node holding the spec's
                # large args saves their whole transfer.  Same min_bytes
                # floor as the submitter/spillback paths — a feasible
                # node holding only a tiny arg must not override
                # pack/spread.
                from .config import get_config as _gc2
                best = policy.pick_by_locality(
                    [(n, n.address, n.resources_total,
                      n.resources_available) for n in cand_nodes],
                    resources, locality,
                    min_bytes=_gc2().object_locality_min_bytes)
                if best is not None:
                    return best
            # Default: hybrid top-k pack-then-spread
            # (reference: hybrid_scheduling_policy.h:50).
            return policy.hybrid_pick(cands, resources)

        # Gray-failure deprioritization AFTER constraint filtering (so a
        # hard label match on a suspect node stays feasible): place on
        # the non-suspect subset when it fits, else fall back to every
        # live node — a suspect node is a last resort, never excluded.
        trusted = [n for n in live
                   if n.suspicion < policy.SUSPECT_THRESHOLD]
        if trusted and len(trusted) < len(live):
            pick = _pick(trusted)
            if pick is not None:
                return pick
        return _pick(live)

    async def _schedule_actor(self, actor: ActorInfo,
                              timeout_s: float | None = None) -> bool:
        """Queue-until-feasible scheduling (reference: GcsActorScheduler keeps
        pending actors and reschedules as resources free up).

        The deadline restarts whenever a new node registers: cloud TPU
        provisioning routinely exceeds the base timeout, and a node arriving
        means the autoscaler is actively delivering the capacity this actor
        is waiting for."""
        spec = actor.spec
        if timeout_s is None:
            from .config import get_config
            timeout_s = float(get_config().actor_scheduling_timeout_s)
        deadline = time.monotonic() + timeout_s
        epoch = self._node_epoch
        node = None
        from .config import get_config as _get_config
        locality = policy.arg_locality(spec.get("args")) \
            if _get_config().object_locality_scheduling_enabled else None
        if locality and max(locality.values()) < \
                _get_config().object_locality_min_bytes:
            locality = None
        while time.monotonic() < deadline:
            if self._node_epoch != epoch:
                epoch = self._node_epoch
                deadline = time.monotonic() + timeout_s
            if actor.state not in (protocol.ACTOR_PENDING,
                                   protocol.ACTOR_RESTARTING):
                return False        # killed while pending/restarting
            node = self._pick_node(spec.get("resources", {}),
                                   spec.get("scheduling_strategy"),
                                   locality=locality)
            if node is not None and node.conn is not None and not node.conn.closed:
                try:
                    result = await node.conn.call("create_actor_worker", spec,
                                                  timeout=120)
                    break
                except (rpc.RpcError, asyncio.TimeoutError) as e:
                    msg = str(e)
                    if "runtime env setup failed" in msg:
                        # A broken env spec never succeeds by retrying —
                        # bury the actor with the installer's error (the
                        # task path fails fast the same way); retrying
                        # would livelock: each fresh install's "in
                        # progress" polls keep the deadline alive.
                        actor.death_cause = msg.split("\n")[0]
                        return False
                    if "setup in progress" in msg:
                        # The node is actively materializing this actor's
                        # runtime env (pip installs can take minutes) —
                        # that's forward progress, not a stall: keep the
                        # deadline fresh like a new-capacity event.
                        deadline = time.monotonic() + timeout_s
                    logger.warning("actor creation on %s failed: %s; retrying",
                                   node.node_id.hex()[:8], msg.split("\n")[0])
            await asyncio.sleep(0.2)
        else:
            logger.warning(
                "actor scheduling timed out: resources=%s node view=%s",
                spec.get("resources"),
                [(n.node_id.hex()[:8], n.alive,
                  n.resources_available,
                  n.conn is not None and not n.conn.closed)
                 for n in self.nodes.values()])
            return False
        actor.state = protocol.ACTOR_ALIVE
        actor.address = result["worker_addr"]
        actor.node_id = node.node_id
        self._log_actor(actor)
        self._publish(protocol.CH_ACTOR, {"event": "alive", "actor": actor.view()})
        return True

    async def h_get_actor(self, conn, p):
        actor = None
        if p.get("actor_id"):
            actor = self.actors.get(p["actor_id"])
        elif p.get("name"):
            aid = self.named_actors.get(p["name"])
            actor = self.actors.get(aid) if aid else None
        if actor is None:
            return None
        if p.get("wait_alive") and actor.state == protocol.ACTOR_PENDING:
            start = time.monotonic()
            while actor.state == protocol.ACTOR_PENDING \
                    and time.monotonic() - start < 30.0:
                await asyncio.sleep(
                    0.01 if time.monotonic() - start < 0.3 else 0.05)
        return actor.view()

    async def h_list_actors(self, conn, p):
        return [a.view() for a in self.actors.values()]

    async def h_kill_actor(self, conn, p):
        self._check_writable(p)
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            # Client-minted handles can be killed before their background
            # registration lands; remember the kill so registration buries
            # the actor instead of scheduling an unreachable orphan.
            now = time.monotonic()
            for aid, ts in list(self._pending_kills.items()):
                if now - ts > 600.0:
                    del self._pending_kills[aid]
                else:
                    break   # insertion-ordered: rest are fresher
            self._pending_kills[p["actor_id"]] = now
            return False
        actor.max_restarts = 0  # explicit kill is permanent
        if actor.state == protocol.ACTOR_ALIVE and actor.address:
            try:
                c = await rpc.connect(tuple(actor.address), retries=1)
                c.notify("kill", {"no_restart": True})
                await c.close()
            except rpc.ConnectionLost:
                pass
        await self._handle_actor_death(actor, "killed via kill_actor")
        return True

    async def h_actor_failed(self, conn, p):
        self._check_writable(p)
        actor = self.actors.get(p["actor_id"])
        if actor is None:
            return False
        await self._handle_actor_death(actor, p.get("reason", "worker died"))
        return True

    async def _handle_actor_death(self, actor: ActorInfo, reason: str):
        """Restart-or-bury (reference: gcs_actor_manager.cc OnActorWorkerDead;
        restart counting at :283)."""
        if actor.state == protocol.ACTOR_DEAD:
            return
        if actor.restarts < actor.max_restarts or actor.max_restarts < 0:
            actor.restarts += 1
            actor.state = protocol.ACTOR_RESTARTING
            actor.address = None
            self._publish(protocol.CH_ACTOR,
                          {"event": "restarting", "actor": actor.view()})
            ok = await self._schedule_actor(actor)
            if ok:
                return
            # Keep a specific cause the scheduler recorded during the
            # failed restart (e.g. a runtime-env install error) — that is
            # the actionable diagnosis, not the original death reason.
            if actor.death_cause:
                reason = f"{reason}; restart failed: {actor.death_cause}"
            else:
                reason = f"{reason}; restart failed"
        actor.state = protocol.ACTOR_DEAD
        actor.death_cause = reason
        actor.address = None
        if actor.name and self.named_actors.get(actor.name) == actor.actor_id:
            del self.named_actors[actor.name]
        self._log_actor(actor)
        self._publish(protocol.CH_ACTOR, {"event": "dead", "actor": actor.view()})

    # ----------------------------------------------------- placement groups --
    async def h_create_placement_group(self, conn, p):
        """Register a PG in PENDING state and place it asynchronously with a
        two-phase bundle reservation across agents (reference:
        gcs_placement_group_manager.cc pending queue +
        gcs_placement_group_scheduler.cc prepare/commit;
        node_manager.proto:471-476).  Returns immediately; clients poll
        get_placement_group / wait on the CH_PG channel."""
        self._check_writable(p)
        pg_id = p["pg_id"]
        if pg_id in self.placement_groups:
            # Retried create (reply lost across a GCS restart): keep the
            # replayed entry and any bundles already committed.
            return {"ok": True, "pg_id": pg_id}
        entry = {
            "pg_id": pg_id,
            "strategy": p.get("strategy", "PACK"),
            "bundle_specs": p["bundles"],     # list of resource dicts
            "bundles": [],                    # filled once placed
            "name": p.get("name", ""),
            "state": "PENDING",
        }
        self.placement_groups[pg_id] = entry
        self._log("pg", entry)
        rpc.spawn(self._place_pg(entry))
        return {"ok": True, "pg_id": pg_id}

    async def _place_pg(self, entry: dict):
        """Retry placement until feasible or the PG is removed (the
        reference keeps infeasible PGs pending forever too)."""
        pg_id = entry["pg_id"]
        bundles = entry["bundle_specs"]

        async def _return(idx, node):
            try:
                await node.conn.call("return_bundle", {
                    "pg_id": pg_id, "bundle_index": idx})
            except (rpc.RpcError, AttributeError, asyncio.TimeoutError):
                pass

        async def _finalize(chosen) -> None:
            """Every bundle is reserved on its node: flip to CREATED — or,
            if the PG was removed mid-placement, hand everything back."""
            if entry["state"] != "PENDING":
                await asyncio.gather(
                    *[_return(i, n) for i, n in enumerate(chosen)])
                return
            entry["bundles"] = [
                {"node_id": n.node_id, "resources": b,
                 "node_addr": list(n.address)}
                for b, n in zip(bundles, chosen)]
            entry["state"] = "CREATED"
            self._log("pg", entry)
            self._pg_event(pg_id).set()
            self._publish(protocol.CH_PG,
                          {"event": "created", "pg_id": pg_id})

        while entry["state"] == "PENDING":
            chosen = self._place_bundles(bundles, entry["strategy"])
            if chosen is None:
                await asyncio.sleep(0.2)
                continue
            if len({n.node_id for n in chosen}) == 1:
                # Single-node placement: prepare+commit collapse into ONE
                # agent RPC (no cross-node atomicity to coordinate) — the
                # dominant shape for small PGs and single-host gangs.
                node = chosen[0]
                try:
                    ok = await node.conn.call("reserve_bundles", {
                        "pg_id": pg_id,
                        "bundles": [{"bundle_index": i, "resources": b}
                                    for i, b in enumerate(bundles)]},
                        timeout=30)
                except (rpc.RpcError, AttributeError, asyncio.TimeoutError):
                    ok = False
                if not ok:
                    await asyncio.sleep(0.2)
                    continue
                await _finalize(chosen)
                return
            # Phase 1: prepare on every node IN PARALLEL; roll back on any
            # failure (a 64-bundle Train worker group pays one agent round
            # trip, not 64).
            async def _prepare(idx, bundle, node):
                try:
                    return await node.conn.call("prepare_bundle", {
                        "pg_id": pg_id, "bundle_index": idx,
                        "resources": bundle}, timeout=30)
                except (rpc.RpcError, AttributeError, asyncio.TimeoutError):
                    return False

            oks = await asyncio.gather(
                *[_prepare(i, b, n)
                  for i, (b, n) in enumerate(zip(bundles, chosen))])
            prepared = [(i, n) for i, (ok, n) in
                        enumerate(zip(oks, chosen)) if ok]
            if not all(oks):
                await asyncio.gather(*[_return(i, n) for i, n in prepared])
                await asyncio.sleep(0.2)
                continue
            # Phase 2: commit (parallel); on any failure return every
            # bundle and retry placement from scratch (a node died between
            # prepare and commit).
            async def _commit(idx, node):
                return await node.conn.call(
                    "commit_bundle", {"pg_id": pg_id, "bundle_index": idx})

            # return_exceptions: every commit must SETTLE before any
            # return_bundle goes out — a plain gather raises on the first
            # failure while sibling commits are still in flight, and a
            # commit processed after its bundle's return would leak the
            # node's resources on the retry.
            outcomes = await asyncio.gather(
                *[_commit(i, n) for i, n in prepared],
                return_exceptions=True)
            if any(isinstance(o, BaseException) for o in outcomes):
                await asyncio.gather(*[_return(i, n) for i, n in prepared])
                await asyncio.sleep(0.2)
                continue
            await _finalize(chosen)
            return

    def _place_bundles(self, bundles, strategy,
                       nodes=None) -> Optional[List[NodeInfo]]:
        alive = (nodes if nodes is not None else
                 [n for n in self.nodes.values() if n.schedulable])
        if not alive:
            return None
        if nodes is None:
            # Gray-failure deprioritization: try the gang on the
            # non-suspect subset first; suspect nodes host new bundles
            # only when the placement cannot succeed without them.
            trusted = [n for n in alive
                       if n.suspicion < policy.SUSPECT_THRESHOLD]
            if trusted and len(trusted) < len(alive):
                got = self._place_bundles(bundles, strategy, nodes=trusted)
                if got is not None:
                    return got
        remaining = {n.node_id: dict(n.resources_available) for n in alive}

        def fits(node, bundle):
            avail = remaining[node.node_id]
            return all(avail.get(k, 0.0) >= v for k, v in bundle.items() if v > 0)

        def take(node, bundle):
            avail = remaining[node.node_id]
            for k, v in bundle.items():
                avail[k] = avail.get(k, 0.0) - v

        chosen: List[NodeInfo] = []
        if strategy in ("PACK", "STRICT_PACK"):
            order = sorted(alive, key=lambda n: -sum(n.resources_available.values()))
            for bundle in bundles:
                placed = None
                for node in (chosen[-1:] if chosen else []) + order:
                    if fits(node, bundle):
                        placed = node
                        break
                if placed is None:
                    return None
                if strategy == "STRICT_PACK" and chosen and placed is not chosen[0]:
                    if fits(chosen[0], bundle):
                        placed = chosen[0]
                    else:
                        return None
                take(placed, bundle)
                chosen.append(placed)
        else:  # SPREAD / STRICT_SPREAD
            used: set = set()
            for bundle in bundles:
                order = sorted(alive, key=lambda n: (n.node_id in used,
                               -sum(remaining[n.node_id].values())))
                placed = None
                for node in order:
                    if strategy == "STRICT_SPREAD" and node.node_id in used:
                        continue
                    if fits(node, bundle):
                        placed = node
                        break
                if placed is None:
                    return None
                take(placed, bundle)
                used.add(placed.node_id)
                chosen.append(placed)
        return chosen

    async def h_remove_placement_group(self, conn, p):
        self._check_writable(p)
        pg = self.placement_groups.pop(p["pg_id"], None)
        if pg is None:
            return False
        pg["state"] = "REMOVED"         # stops a pending _place_pg loop
        ev = self._pg_events.pop(p["pg_id"], None)
        if ev is not None:
            ev.set()                    # wake pending waiters (-> None)
        self._log("pg_del", p["pg_id"])

        async def _return(idx, bundle):
            node = self.nodes.get(bundle["node_id"])
            if node and node.conn and not node.conn.closed:
                try:
                    await node.conn.call(
                        "return_bundle",
                        {"pg_id": p["pg_id"], "bundle_index": idx})
                except rpc.RpcError:
                    pass

        # Bundles return in parallel — removal latency is one agent round
        # trip, not one per bundle.
        await asyncio.gather(*[_return(i, b)
                               for i, b in enumerate(pg["bundles"])])
        return True

    def _pg_event(self, pg_id) -> asyncio.Event:
        ev = self._pg_events.get(pg_id)
        if ev is None:
            ev = self._pg_events[pg_id] = asyncio.Event()
        return ev

    async def h_get_placement_group(self, conn, p):
        entry = self.placement_groups.get(p["pg_id"])
        if entry is None or not p.get("wait_created"):
            return entry
        # Server-side event wait: the waiter wakes the moment _place_pg
        # publishes CREATED (or removal fires the event) — no poll loop
        # (reference: clients block on the CreatePlacementGroup reply /
        # ready future).
        if entry["state"] == "PENDING":
            try:
                await asyncio.wait_for(
                    self._pg_event(p["pg_id"]).wait(),
                    min(p.get("timeout_s", 10.0), 60.0))
            except asyncio.TimeoutError:
                pass
        # Removal during the wait pops the table; honor the None-means-
        # removed contract rather than returning the orphaned entry.
        return self.placement_groups.get(p["pg_id"])

    async def h_list_placement_groups(self, conn, p):
        return list(self.placement_groups.values())

    async def h_get_cluster_info(self, conn, p):
        return {
            "nodes": [n.view() for n in self.nodes.values()],
            "num_actors": len(self.actors),
            "num_jobs": len(self.jobs),
            protocol.EPOCH_KEY: self.epoch,
            "failovers": self._failover_count,
        }


class GcsStandby:
    """Warm-standby GCS: tails the primary's journal (shared-path follow
    mode — the single-host equivalent of a `journal_tail` streaming RPC),
    keeps hot replicas of every journaled table in an unstarted
    GcsServer, and holds back from serving until the primary's lease has
    gone a full TTL without renewal.  Takeover then: drain the un-tailed
    journal suffix, bump the cluster epoch (journaled before a single
    request is served), take over the advertised address, and start
    serving — clients re-home through resolve_gcs_address() on their
    next reconnect attempt (docs/control_plane.md §8)."""

    def __init__(self, journal_path: str, ha_dir: str,
                 host: str = "127.0.0.1", port: int = 0):
        self.journal_path = journal_path
        self.ha_dir = ha_dir
        self.server = GcsServer(host, port, journal_path, ha_dir=ha_dir)
        self.tailer = JournalTailer(journal_path)
        self.promoted = False
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    def _apply(self, records, reset: bool) -> None:
        if reset:
            # Compaction replaced the journal under us: the new file is
            # self-contained (snapshot + suffix), so rebuild from zero.
            self.server._reset_tables()
        self.server._replay(records)

    async def run_until_takeover(self) -> Optional[GcsServer]:
        """Tail until the lease expires, then take over and return the
        (started) server; returns None if stop() was called first."""
        cfg = get_config()
        poll = cfg.gcs_standby_poll_ms / 1000.0
        lease_path = os.path.join(self.ha_dir, protocol.GCS_LEASE_FILE)
        sb_path = os.path.join(self.ha_dir, protocol.GCS_STANDBY_FILE)
        last_progress = 0.0
        while not self._stop:
            records, reset = self.tailer.poll()
            if records or reset:
                self._apply(records, reset)
            now = time.time()
            if now - last_progress >= 1.0:
                # Tail-progress breadcrumb: the PRIMARY exports it as
                # the standby-lag gauges (it owns the metrics endpoint).
                GcsServer._write_json_atomic(sb_path, {
                    "lag_bytes": self.tailer.lag_bytes(),
                    "ts": now, "pid": os.getpid()})
                last_progress = now
            lease = GcsServer._read_json(lease_path)
            if lease is not None:
                age = now - float(lease.get("renewed", 0.0))
                ttl = float(lease.get("ttl_s",
                                      cfg.gcs_lease_ttl_s))
                if age > ttl:
                    await self._take_over(lease, age)
                    return self.server
            await asyncio.sleep(poll)
        self.tailer.close()
        return None

    async def _take_over(self, stale_lease: dict, lease_age_s: float):
        # Final drain: whatever the dead primary flushed before the
        # lease lapsed must be in the replica before we bump the epoch.
        for _ in range(8):
            records, reset = self.tailer.poll()
            if not records and not reset:
                break
            self._apply(records, reset)
        self.tailer.close()
        srv = self.server
        prev_epoch = srv.epoch
        srv.epoch = max(srv.epoch, int(stale_lease.get("epoch", 0))) + 1
        srv._failover_count += 1
        srv._replayed = True        # tables are hot; start() must not re-replay
        logger.warning(
            "GCS standby taking over: lease stale %.2fs, epoch %d -> %d",
            lease_age_s, prev_epoch, srv.epoch)
        addr = await srv.start()    # journals the epoch bump, claims the
        self.promoted = True        # lease, rewrites the address file
        # Failover is an anomaly by definition: capture a black-box
        # bundle (diag-gcs_failover-*) with the takeover context.
        srv._ingest_anomaly({
            "kind": "gcs_failover", "daemon": "gcs",
            "epoch": srv.epoch, "prev_epoch": prev_epoch,
            "lease_age_s": round(lease_age_s, 3),
            "ex_primary_pid": int(stale_lease.get("owner_pid") or 0),
            "address": list(addr)})
        return addr


async def _amain(args):
    rpc.enable_eager_tasks()
    from .config import Config, get_config as _gcfg, set_config
    if args.system_config:
        set_config(Config(json.loads(args.system_config)))
    chaos_spec = _gcfg().rpc_chaos
    if chaos_spec:
        rpc.enable_chaos(chaos_spec)
    rpc.enable_link_chaos(_gcfg().link_chaos)
    rpc.enable_native_framer(_gcfg().rpc_native_framer)
    rpc.set_default_call_timeout(_gcfg().control_call_timeout_s)

    def _ready(payload: dict) -> None:
        if not args.ready_file:
            return
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, args.ready_file)

    if args.standby:
        if not args.journal:
            raise SystemExit("--standby requires --journal")
        ha_dir = args.ha_dir or os.path.dirname(args.journal)
        standby = GcsStandby(args.journal, ha_dir, port=args.port)
        # Readiness for a standby means "tailing", not "serving".
        _ready({"standby": True, "pid": os.getpid()})
        server = await standby.run_until_takeover()
        if server is None:
            return
        _ready({"address": list(server.address), "promoted": True,
                protocol.EPOCH_KEY: server.epoch, "pid": os.getpid()})
    else:
        server = GcsServer(port=args.port,
                           journal_path=args.journal or None,
                           ha_dir=args.ha_dir or None)
        addr = await server.start()
        # Signal readiness to the parent via a file it watches.
        _ready({"address": list(addr), protocol.EPOCH_KEY: server.epoch,
                "pid": os.getpid()})
    # Serve until fenced: a successor epoch in the lease file means a
    # standby took over while this process was frozen/partitioned — the
    # only correct move left is to stop touching the world and exit.
    await server.fenced_event.wait()
    logger.error("exiting: fenced by a newer-epoch primary")
    try:
        await asyncio.wait_for(server.close(), 5)
    except asyncio.TimeoutError:
        pass
    os._exit(3)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--ready-file", default="")
    parser.add_argument("--journal", default="")
    parser.add_argument("--ha-dir", default="",
                        help="shared dir for the HA lease + advertised-"
                             "address files; empty disables the lease")
    parser.add_argument("--standby", action="store_true",
                        help="run as warm standby: tail the journal, "
                             "serve only after lease-expiry takeover")
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument("--system-config", default="")
    args = parser.parse_args()
    logging.basicConfig(level=args.log_level, format="%(asctime)s %(levelname)s %(name)s %(message)s")
    from .node import install_daemon_profiler
    install_daemon_profiler("gcs")
    from .auth import require_process_token
    require_process_token("gcs")
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
