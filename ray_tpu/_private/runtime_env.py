"""Runtime environments: working_dir / py_modules / env_vars / pip / uv.

Reference surface: python/ray/_private/runtime_env/ — the driver packages
local directories into content-addressed zips uploaded to the GCS KV
(reference: runtime_env/packaging.py gcs:// URIs), and each node's agent
materializes URIs into a per-session cache before spawning workers
(reference: runtime_env agent creating env on each node, URI caching).

Package plugins (reference: runtime_env/pip.py, uv.py): `pip` / `uv`
install a spec's packages into a per-node content-addressed target dir
(`pip install --target`), prepended to the worker's PYTHONPATH — cached
by spec hash so N workers pay one install.  Air-gapped clusters pass
`find_links` (a local wheel dir) and installs run `--no-index`, which is
also how the tests exercise the plugin without network.  Unsupported
plugins (conda/container) still raise up front rather than silently
no-op.
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile
from typing import Dict, List, Optional, Tuple

PKG_KV_NS = "runtime_env_pkg"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PKG_BYTES = 512 * 1024 * 1024

_SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules",
                   "working_dir_uri", "py_modules_uris", "config",
                   "pip", "uv"}


def _normalize_pkg_spec(spec, kind: str) -> dict:
    """Accept the reference's shapes — a list of requirements, or a dict
    {"packages": [...], "find_links": dir} — and normalize for stable
    hashing (reference: pip.py RuntimeEnvPlugin validation)."""
    if isinstance(spec, (list, tuple)):
        spec = {"packages": list(spec)}
    if not isinstance(spec, dict) or not spec.get("packages"):
        raise ValueError(
            f"runtime_env[{kind!r}] must be a non-empty list of "
            "requirements or {'packages': [...], 'find_links': dir}")
    out = {"packages": sorted(str(p) for p in spec["packages"])}
    fl = spec.get("find_links")
    if fl:
        out["find_links"] = os.path.abspath(str(fl))
    return out


def _zip_dir(path: str) -> bytes:
    import io
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in sorted(dirs) if d not in _EXCLUDE_DIRS]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, base)
                zf.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_PKG_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PKG_BYTES}); exclude large data directories")
    return data


def _upload_dir(core, path: str) -> str:
    """Zip + content-address + upload once; returns the gcs:// URI."""
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    data = _zip_dir(path)
    digest = hashlib.sha1(data).hexdigest()
    uri = f"gcs://{digest}"
    if not core.gcs_call("kv_exists", {"ns": PKG_KV_NS, "key": digest}):
        core.gcs_call("kv_put", {"ns": PKG_KV_NS, "key": digest,
                                 "value": data, "overwrite": False})
    return uri


def package_runtime_env(core, runtime_env: Optional[dict]) -> Optional[dict]:
    """Driver-side: validate + rewrite local paths to uploaded URIs."""
    if not runtime_env:
        return runtime_env
    unknown = set(runtime_env) - _SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env key(s) {sorted(unknown)}; supported: "
            f"{sorted(_SUPPORTED_KEYS)} (pip/conda/container are not "
            "available in this runtime)")
    out = dict(runtime_env)
    wd = out.pop("working_dir", None)
    if wd:
        if isinstance(wd, str) and wd.startswith("gcs://"):
            out["working_dir_uri"] = wd
        else:
            out["working_dir_uri"] = _upload_dir(core, wd)
    mods = out.pop("py_modules", None)
    if mods:
        uris = []
        for m in mods:
            if isinstance(m, str) and m.startswith("gcs://"):
                uris.append(m)
            else:
                uris.append(_upload_dir(core, m))
        out["py_modules_uris"] = uris
    if "pip" in out and "uv" in out:
        raise ValueError("runtime_env cannot set both 'pip' and 'uv'")
    for kind in ("pip", "uv"):
        if kind in out:
            out[kind] = _normalize_pkg_spec(out[kind], kind)
    return out


def runtime_env_hash(runtime_env: Optional[dict]) -> bytes:
    """Stable digest for scheduling keys: tasks with different runtime
    envs must not share leased workers."""
    if not runtime_env:
        return b""
    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True, default=str).encode()
    ).digest()[:8]


class UriCache:
    """Agent-side URI materialization with a per-session extract cache
    (reference: runtime_env URI cache + refcounting; here cache entries
    live for the session)."""

    def __init__(self, cache_root: str):
        self.cache_root = cache_root
        self._inflight: Dict[str, "asyncio.Future"] = {}
        # runtime_env hash -> asyncio.Task running setup() (poll_setup)
        self._setups: Dict[bytes, "asyncio.Task"] = {}

    async def ensure(self, gcs_conn, uri: str) -> str:
        """Download+extract `gcs://<digest>` once; concurrent callers for
        the same digest share one in-flight fetch (a 512MB package must
        not be pulled N times by a task fan-out)."""
        import asyncio
        assert uri.startswith("gcs://"), uri
        digest = uri[len("gcs://"):]
        dest = os.path.join(self.cache_root, digest)
        if os.path.isdir(dest):
            return dest
        fut = self._inflight.get(digest)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[digest] = fut
        try:
            data = await gcs_conn.call(
                "kv_get", {"ns": PKG_KV_NS, "key": digest}, timeout=120)
            if data is None:
                raise RuntimeError(
                    f"runtime_env package {uri} not found in GCS")

            def _extract():
                if os.path.isdir(dest):
                    return
                tmp = dest + f".tmp{os.getpid()}"
                os.makedirs(tmp, exist_ok=True)
                import io
                with zipfile.ZipFile(io.BytesIO(bytes(data))) as zf:
                    zf.extractall(tmp)
                try:
                    os.rename(tmp, dest)
                except OSError:
                    import shutil
                    shutil.rmtree(tmp, ignore_errors=True)
            await asyncio.get_running_loop().run_in_executor(None, _extract)
            fut.set_result(dest)
            return dest
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            self._inflight.pop(digest, None)
            if not fut.done():
                fut.cancel()

    async def ensure_packages(self, spec: dict, kind: str) -> str:
        """Install a pip/uv spec into a content-addressed target dir once
        per node (reference: pip.py/uv.py create-once + URI cache).
        Returns the directory to prepend to PYTHONPATH."""
        import asyncio
        import shutil
        import subprocess
        import sys

        digest = hashlib.sha1(
            json.dumps({"kind": kind, **spec}, sort_keys=True).encode()
        ).hexdigest()
        dest = os.path.join(self.cache_root, "pkg_envs", digest)
        if os.path.isdir(dest):
            return dest
        key = f"pkg:{digest}"
        fut = self._inflight.get(key)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        try:
            if kind == "uv":
                uv = shutil.which("uv")
                if uv is None:
                    raise RuntimeError(
                        "runtime_env['uv'] requested but no `uv` binary "
                        "is on PATH on this node; use the 'pip' plugin "
                        "or install uv")
                # --python pins resolution to the interpreter the workers
                # actually run; without it uv discovers whatever venv the
                # agent's shell had (or errors with none).
                cmd = [uv, "pip", "install", "--python", sys.executable,
                       "--target"]
            else:
                cmd = [sys.executable, "-m", "pip", "install",
                       "--no-warn-script-location", "--target"]
            tmp = dest + f".tmp{os.getpid()}_{os.urandom(3).hex()}"
            full = cmd + [tmp]
            if spec.get("find_links"):
                # Air-gapped path: local wheels only, never the index.
                full += ["--no-index", "--find-links", spec["find_links"]]
            full += spec["packages"]

            def _install():
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                try:
                    proc = subprocess.run(full, capture_output=True,
                                          text=True, timeout=600)
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"runtime_env {kind} install failed "
                            f"(packages={spec['packages']}): "
                            f"{proc.stderr.strip()[-2000:]}")
                    os.replace(tmp, dest)
                except BaseException:
                    # Timeout/anything: never leave a half-populated
                    # staging dir behind (a retry must start clean).
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
            await asyncio.get_running_loop().run_in_executor(None, _install)
            fut.set_result(dest)
            return dest
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            self._inflight.pop(key, None)
            if not fut.done():
                fut.cancel()

    def poll_setup(self, gcs_conn, runtime_env: Optional[dict]):
        """Non-blocking env materialization for the lease-grant path
        (reference: the raylet asks its runtime-env agent and retries the
        lease rather than blocking the grant RPC on a pip install).

        Returns (status, payload): ('ready', (env_extra, cwd)) when every
        piece is materialized; ('pending', None) with the setup running
        in the background; ('failed', error_str) when setup errored (the
        failure is consumed — a later poll retries)."""
        import asyncio
        if not runtime_env or (not runtime_env.get("working_dir_uri")
                               and not runtime_env.get("py_modules_uris")
                               and not runtime_env.get("pip")
                               and not runtime_env.get("uv")):
            # Only env_vars (or nothing): pure dict-building, no IO —
            # answer inline so the common case stays single-round-trip.
            env_extra = {k: str(v) for k, v in
                         (runtime_env or {}).get("env_vars", {}).items()}
            return "ready", (env_extra, None)
        key = runtime_env_hash(runtime_env)
        task = self._setups.get(key)
        if task is None:
            task = asyncio.ensure_future(self.setup(gcs_conn, runtime_env))
            self._setups[key] = task
            return "pending", None
        if not task.done():
            return "pending", None
        if task.exception() is not None:
            err = str(task.exception())
            del self._setups[key]    # a later poll starts a fresh attempt
            return "failed", err
        return "ready", task.result()

    async def setup(self, gcs_conn, runtime_env: Optional[dict]
                    ) -> Tuple[Dict[str, str], Optional[str]]:
        """Materialize a worker's runtime env. Returns (env_extra, cwd)."""
        env_extra: Dict[str, str] = {}
        cwd: Optional[str] = None
        renv = runtime_env or {}
        for k, v in (renv.get("env_vars") or {}).items():
            env_extra[k] = str(v)
        py_paths: List[str] = []
        wd_uri = renv.get("working_dir_uri")
        if wd_uri:
            cwd = await self.ensure(gcs_conn, wd_uri)
            py_paths.append(cwd)
        for uri in renv.get("py_modules_uris") or []:
            py_paths.append(await self.ensure(gcs_conn, uri))
        for kind in ("pip", "uv"):
            if renv.get(kind):
                py_paths.append(
                    await self.ensure_packages(renv[kind], kind))
        if py_paths:
            existing = env_extra.get("PYTHONPATH",
                                     os.environ.get("PYTHONPATH", ""))
            env_extra["PYTHONPATH"] = os.pathsep.join(
                py_paths + ([existing] if existing else []))
        return env_extra, cwd
