"""Runtime environments: working_dir / py_modules / env_vars / pip / uv.

Reference surface: python/ray/_private/runtime_env/ — the driver packages
local directories into content-addressed zips uploaded to the GCS KV
(reference: runtime_env/packaging.py gcs:// URIs), and each node's agent
materializes URIs into a per-session cache before spawning workers
(reference: runtime_env agent creating env on each node, URI caching).

Package plugins (reference: runtime_env/pip.py, uv.py): `pip` / `uv`
install a spec's packages into a per-node content-addressed target dir
(`pip install --target`), prepended to the worker's PYTHONPATH — cached
by spec hash so N workers pay one install.  Air-gapped clusters pass
`find_links` (a local wheel dir) and installs run `--no-index`, which is
also how the tests exercise the plugin without network.

Interpreter plugins: `conda` (reference: runtime_env/conda.py) switches
the worker to an existing named/prefix env's python, or creates a
content-addressed env from a spec dict; `container` (reference:
runtime_env/container.py) launches the worker through podman/docker run
with the session bind-mounted and host IPC/network (so the shm store and
TCP control plane work unchanged).  Both route through env_extra keys
(RAY_TPU_WORKER_PYTHON / RAY_TPU_WORKER_CONTAINER) the agent's spawn
path consumes.
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile
from typing import Dict, List, Optional, Tuple

PKG_KV_NS = "runtime_env_pkg"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PKG_BYTES = 512 * 1024 * 1024

_SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules",
                   "working_dir_uri", "py_modules_uris", "config",
                   "pip", "uv", "conda", "container"}


def _normalize_pkg_spec(spec, kind: str) -> dict:
    """Accept the reference's shapes — a list of requirements, or a dict
    {"packages": [...], "find_links": dir} — and normalize for stable
    hashing (reference: pip.py RuntimeEnvPlugin validation)."""
    if isinstance(spec, (list, tuple)):
        spec = {"packages": list(spec)}
    if not isinstance(spec, dict) or not spec.get("packages"):
        raise ValueError(
            f"runtime_env[{kind!r}] must be a non-empty list of "
            "requirements or {'packages': [...], 'find_links': dir}")
    out = {"packages": sorted(str(p) for p in spec["packages"])}
    fl = spec.get("find_links")
    if fl:
        out["find_links"] = os.path.abspath(str(fl))
    return out


def _zip_dir(path: str) -> bytes:
    import io
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in sorted(dirs) if d not in _EXCLUDE_DIRS]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, base)
                zf.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_PKG_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PKG_BYTES}); exclude large data directories")
    return data


def _upload_dir(core, path: str) -> str:
    """Zip + content-address + upload once; returns the gcs:// URI."""
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    data = _zip_dir(path)
    digest = hashlib.sha1(data).hexdigest()
    uri = f"gcs://{digest}"
    if not core.gcs_call("kv_exists", {"ns": PKG_KV_NS, "key": digest}):
        core.gcs_call("kv_put", {"ns": PKG_KV_NS, "key": digest,
                                 "value": data, "overwrite": False})
    return uri


def package_runtime_env(core, runtime_env: Optional[dict]) -> Optional[dict]:
    """Driver-side: validate + rewrite local paths to uploaded URIs."""
    if not runtime_env:
        return runtime_env
    unknown = set(runtime_env) - _SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env key(s) {sorted(unknown)}; supported: "
            f"{sorted(_SUPPORTED_KEYS)}")
    out = dict(runtime_env)
    wd = out.pop("working_dir", None)
    if wd:
        if isinstance(wd, str) and wd.startswith("gcs://"):
            out["working_dir_uri"] = wd
        else:
            out["working_dir_uri"] = _upload_dir(core, wd)
    mods = out.pop("py_modules", None)
    if mods:
        uris = []
        for m in mods:
            if isinstance(m, str) and m.startswith("gcs://"):
                uris.append(m)
            else:
                uris.append(_upload_dir(core, m))
        out["py_modules_uris"] = uris
    if "pip" in out and "uv" in out:
        raise ValueError("runtime_env cannot set both 'pip' and 'uv'")
    for kind in ("pip", "uv"):
        if kind in out:
            out[kind] = _normalize_pkg_spec(out[kind], kind)
    if "conda" in out:
        if "pip" in out or "uv" in out:
            # Same exclusivity as the reference (conda envs carry their
            # own pip section; reference: runtime_env/validation.py).
            raise ValueError(
                "runtime_env cannot combine 'conda' with 'pip'/'uv' — "
                "put pip packages inside the conda spec's dependencies")
        if "container" in out:
            raise ValueError(
                "runtime_env cannot combine 'conda' with 'container' — "
                "the container runs the image's interpreter; bake the "
                "env into the image instead")
        out["conda"] = _normalize_conda_spec(out["conda"])
    if "container" in out:
        out["container"] = _normalize_container_spec(out["container"])
    return out


def _normalize_conda_spec(spec) -> dict:
    """str -> existing named/prefix env; dict -> env created from the
    spec's dependencies (reference: runtime_env/conda.py — named env
    reuse, or create-from-yaml with a nested pip section)."""
    if isinstance(spec, str):
        if not spec:
            raise ValueError("runtime_env['conda'] name must be non-empty")
        return {"name": spec}
    if isinstance(spec, dict):
        unknown = set(spec) - {"dependencies", "channels", "name"}
        if unknown:
            # Same strictness as top-level runtime_env keys: silently
            # dropping e.g. a misspelled 'channels' would change which
            # packages resolve with no error.
            raise ValueError(
                f"unsupported conda spec key(s) {sorted(unknown)}; "
                "supported: dependencies, channels, name")
        deps = spec.get("dependencies")
        if not deps and spec.get("name"):
            # {'name': ...} alone = reuse an existing env, same as the
            # plain-string shape (environment.yml carries a name).
            if spec.get("channels"):
                raise ValueError(
                    "conda spec 'channels' has no effect when reusing an "
                    "existing env by name — drop it, or provide "
                    "'dependencies' to build an env")
            return {"name": str(spec["name"])}
        if not deps or not isinstance(deps, (list, tuple)):
            raise ValueError(
                "runtime_env['conda'] dict must carry a non-empty "
                "'dependencies' list (conda yaml shape), or just a "
                "'name' to reuse an existing env")
        chans = spec.get("channels")
        if chans is not None and not isinstance(chans, (list, tuple)):
            raise ValueError(
                "conda spec 'channels' must be a list of channel names")
        norm: List = []
        for d in deps:
            if isinstance(d, dict):
                pip = d.get("pip")
                if not isinstance(pip, (list, tuple)):
                    raise ValueError(
                        "nested conda dependency dicts must be "
                        "{'pip': [...]}")
                norm.append({"pip": sorted(str(p) for p in pip)})
            else:
                norm.append(str(d))
        out = {"dependencies":
               sorted(norm, key=lambda d: json.dumps(d, sort_keys=True))}
        if chans:
            out["channels"] = [str(c) for c in chans]
        # A 'name' alongside dependencies is cosmetic in conda yaml (the
        # env here is content-addressed); keep it out of the normalized
        # spec so identical dependency sets share one cached env.
        return out
    raise ValueError(
        "runtime_env['conda'] must be an env name (str) or a spec dict")


def _normalize_container_spec(spec) -> dict:
    """{'image': ..., 'run_options': [...], 'runtime': binary} (reference:
    runtime_env/container.py + image_uri.py — podman run with the session
    mounted; 'runtime' selects the engine binary and exists mainly so
    tests can inject a fake)."""
    if isinstance(spec, str):
        spec = {"image": spec}
    if not isinstance(spec, dict) or not spec.get("image"):
        raise ValueError(
            "runtime_env['container'] must be an image name or "
            "{'image': ..., 'run_options': [...]}")
    out = {"image": str(spec["image"])}
    ro = spec.get("run_options")
    if ro:
        if not isinstance(ro, (list, tuple)):
            raise ValueError("container run_options must be a list")
        out["run_options"] = [str(o) for o in ro]
    if spec.get("runtime"):
        out["runtime"] = str(spec["runtime"])
    return out


def runtime_env_hash(runtime_env: Optional[dict]) -> bytes:
    """Stable digest for scheduling keys: tasks with different runtime
    envs must not share leased workers."""
    if not runtime_env:
        return b""
    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True, default=str).encode()
    ).digest()[:8]


class UriCache:
    """Agent-side URI materialization with a per-session extract cache
    (reference: runtime_env URI cache + refcounting; here cache entries
    live for the session)."""

    def __init__(self, cache_root: str):
        self.cache_root = cache_root
        self._inflight: Dict[str, "asyncio.Future"] = {}
        # runtime_env hash -> asyncio.Task running setup() (poll_setup)
        self._setups: Dict[bytes, "asyncio.Task"] = {}

    async def ensure(self, gcs_conn, uri: str) -> str:
        """Download+extract `gcs://<digest>` once; concurrent callers for
        the same digest share one in-flight fetch (a 512MB package must
        not be pulled N times by a task fan-out)."""
        import asyncio
        assert uri.startswith("gcs://"), uri
        digest = uri[len("gcs://"):]
        dest = os.path.join(self.cache_root, digest)
        if os.path.isdir(dest):
            return dest
        fut = self._inflight.get(digest)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[digest] = fut
        try:
            data = await gcs_conn.call(
                "kv_get", {"ns": PKG_KV_NS, "key": digest}, timeout=120)
            if data is None:
                raise RuntimeError(
                    f"runtime_env package {uri} not found in GCS")

            def _extract():
                if os.path.isdir(dest):
                    return
                tmp = dest + f".tmp{os.getpid()}"
                os.makedirs(tmp, exist_ok=True)
                import io
                with zipfile.ZipFile(io.BytesIO(bytes(data))) as zf:
                    zf.extractall(tmp)
                try:
                    os.rename(tmp, dest)
                except OSError:
                    import shutil
                    shutil.rmtree(tmp, ignore_errors=True)
            await asyncio.get_running_loop().run_in_executor(None, _extract)
            fut.set_result(dest)
            return dest
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            self._inflight.pop(digest, None)
            if not fut.done():
                fut.cancel()

    async def ensure_packages(self, spec: dict, kind: str) -> str:
        """Install a pip/uv spec into a content-addressed target dir once
        per node (reference: pip.py/uv.py create-once + URI cache).
        Returns the directory to prepend to PYTHONPATH."""
        import asyncio
        import shutil
        import subprocess
        import sys

        digest = hashlib.sha1(
            json.dumps({"kind": kind, **spec}, sort_keys=True).encode()
        ).hexdigest()
        dest = os.path.join(self.cache_root, "pkg_envs", digest)
        if os.path.isdir(dest):
            return dest
        key = f"pkg:{digest}"
        fut = self._inflight.get(key)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        try:
            if kind == "uv":
                uv = shutil.which("uv")
                if uv is None:
                    raise RuntimeError(
                        "runtime_env['uv'] requested but no `uv` binary "
                        "is on PATH on this node; use the 'pip' plugin "
                        "or install uv")
                # --python pins resolution to the interpreter the workers
                # actually run; without it uv discovers whatever venv the
                # agent's shell had (or errors with none).
                cmd = [uv, "pip", "install", "--python", sys.executable,
                       "--target"]
            else:
                cmd = [sys.executable, "-m", "pip", "install",
                       "--no-warn-script-location", "--target"]
            tmp = dest + f".tmp{os.getpid()}_{os.urandom(3).hex()}"
            full = cmd + [tmp]
            if spec.get("find_links"):
                # Air-gapped path: local wheels only, never the index.
                full += ["--no-index", "--find-links", spec["find_links"]]
            full += spec["packages"]

            def _install():
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                try:
                    proc = subprocess.run(full, capture_output=True,
                                          text=True, timeout=600)
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"runtime_env {kind} install failed "
                            f"(packages={spec['packages']}): "
                            f"{proc.stderr.strip()[-2000:]}")
                    os.replace(tmp, dest)
                except BaseException:
                    # Timeout/anything: never leave a half-populated
                    # staging dir behind (a retry must start clean).
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
            await asyncio.get_running_loop().run_in_executor(None, _install)
            fut.set_result(dest)
            return dest
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            self._inflight.pop(key, None)
            if not fut.done():
                fut.cancel()

    @staticmethod
    def _resolve_conda_python(name: str) -> str:
        """Python executable of an existing conda env, by name or prefix
        path (reference: conda.py get_conda_env_dir — $CONDA_PREFIX/envs,
        the conda base install, ~/.conda/envs).  RAY_TPU_CONDA_ROOT lets
        tests (and nonstandard installs) add a search root."""
        import shutil
        candidates: List[str] = []
        if os.path.isabs(name):
            candidates.append(name)
        else:
            roots: List[str] = []
            if os.environ.get("RAY_TPU_CONDA_ROOT"):
                roots.append(os.path.join(
                    os.environ["RAY_TPU_CONDA_ROOT"], "envs"))
            if os.environ.get("CONDA_PREFIX"):
                base = os.environ["CONDA_PREFIX"]
                # CONDA_PREFIX is the ACTIVE env; envs live in base/envs.
                roots.append(os.path.join(base, "envs"))
                roots.append(os.path.join(os.path.dirname(
                    os.path.dirname(base)), "envs"))
            conda_exe = os.environ.get("CONDA_EXE") or shutil.which("conda")
            if conda_exe:
                roots.append(os.path.join(os.path.dirname(
                    os.path.dirname(conda_exe)), "envs"))
            roots.append(os.path.expanduser("~/.conda/envs"))
            candidates.extend(os.path.join(r, name) for r in roots)
        for prefix in candidates:
            py = os.path.join(prefix, "bin", "python")
            if os.path.exists(py):
                return py
        raise RuntimeError(
            f"runtime_env['conda'] env {name!r} not found on this node "
            f"(searched {candidates})")

    async def ensure_conda(self, spec: dict) -> str:
        """Python executable for a conda runtime env: named envs resolve
        in place; dict specs create a content-addressed env once per node
        (reference: conda.py create-from-yaml + cache)."""
        import asyncio
        import shutil
        import subprocess

        if "name" in spec:
            return self._resolve_conda_python(spec["name"])
        digest = hashlib.sha1(
            json.dumps(spec, sort_keys=True).encode()).hexdigest()
        dest = os.path.join(self.cache_root, "conda_envs", digest)
        py = os.path.join(dest, "bin", "python")
        if os.path.exists(py):
            return py
        key = f"conda:{digest}"
        fut = self._inflight.get(key)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[key] = fut
        try:
            conda = os.environ.get("CONDA_EXE") or shutil.which("conda")
            if conda is None:
                raise RuntimeError(
                    "runtime_env['conda'] spec requires the `conda` "
                    "binary on this node; name an existing env or use "
                    "the 'pip' plugin")
            pkgs = [d for d in spec["dependencies"] if isinstance(d, str)]
            pips: List[str] = []
            for d in spec["dependencies"]:
                if isinstance(d, dict):
                    pips.extend(d["pip"])
            chans: List[str] = []
            for c in spec.get("channels") or []:
                chans += ["-c", c]

            def _create():
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                tmp = dest + f".tmp{os.getpid()}"
                try:
                    proc = subprocess.run(
                        [conda, "create", "-y", "-p", tmp] + chans + pkgs,
                        capture_output=True, text=True, timeout=1800)
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"conda create failed: "
                            f"{proc.stderr.strip()[-2000:]}")
                    if not os.path.exists(
                            os.path.join(tmp, "bin", "python")):
                        raise RuntimeError(
                            "conda env spec produced no bin/python — "
                            "include 'python' (e.g. 'python=3.12') in "
                            f"dependencies: {spec['dependencies']}")
                    if pips:
                        proc = subprocess.run(
                            [os.path.join(tmp, "bin", "python"), "-m",
                             "pip", "install"] + pips,
                            capture_output=True, text=True, timeout=1800)
                        if proc.returncode != 0:
                            raise RuntimeError(
                                f"conda env pip install failed: "
                                f"{proc.stderr.strip()[-2000:]}")
                    os.replace(tmp, dest)
                except BaseException:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
            await asyncio.get_running_loop().run_in_executor(None, _create)
            fut.set_result(py)
            return py
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            self._inflight.pop(key, None)
            if not fut.done():
                fut.cancel()

    def poll_setup(self, gcs_conn, runtime_env: Optional[dict]):
        """Non-blocking env materialization for the lease-grant path
        (reference: the raylet asks its runtime-env agent and retries the
        lease rather than blocking the grant RPC on a pip install).

        Returns (status, payload): ('ready', (env_extra, cwd)) when every
        piece is materialized; ('pending', None) with the setup running
        in the background; ('failed', error_str) when setup errored (the
        failure is consumed — a later poll retries)."""
        import asyncio
        if not runtime_env or not any(
                runtime_env.get(k) for k in
                ("working_dir_uri", "py_modules_uris", "pip", "uv",
                 "conda", "container")):
            # Only env_vars (or nothing): pure dict-building, no IO —
            # answer inline so the common case stays single-round-trip.
            env_extra = {k: str(v) for k, v in
                         (runtime_env or {}).get("env_vars", {}).items()}
            return "ready", (env_extra, None)
        key = runtime_env_hash(runtime_env)
        task = self._setups.get(key)
        if task is None:
            task = asyncio.ensure_future(self.setup(gcs_conn, runtime_env))
            self._setups[key] = task
            return "pending", None
        if not task.done():
            return "pending", None
        if task.exception() is not None:
            err = str(task.exception())
            del self._setups[key]    # a later poll starts a fresh attempt
            return "failed", err
        return "ready", task.result()

    async def setup(self, gcs_conn, runtime_env: Optional[dict]
                    ) -> Tuple[Dict[str, str], Optional[str]]:
        """Materialize a worker's runtime env. Returns (env_extra, cwd)."""
        env_extra: Dict[str, str] = {}
        cwd: Optional[str] = None
        renv = runtime_env or {}
        for k, v in (renv.get("env_vars") or {}).items():
            env_extra[k] = str(v)
        py_paths: List[str] = []
        wd_uri = renv.get("working_dir_uri")
        if wd_uri:
            cwd = await self.ensure(gcs_conn, wd_uri)
            py_paths.append(cwd)
        for uri in renv.get("py_modules_uris") or []:
            py_paths.append(await self.ensure(gcs_conn, uri))
        for kind in ("pip", "uv"):
            if renv.get(kind):
                py_paths.append(
                    await self.ensure_packages(renv[kind], kind))
        if renv.get("conda"):
            # Interpreter override: the spawned worker execs with the
            # env's python (the zygote fork path is skipped for env'd
            # workers, so the override always takes effect).  ray_tpu
            # itself rides PYTHONPATH into the foreign interpreter.
            env_extra["RAY_TPU_WORKER_PYTHON"] = \
                await self.ensure_conda(renv["conda"])
            import ray_tpu
            py_paths.append(os.path.dirname(
                os.path.dirname(os.path.abspath(ray_tpu.__file__))))
        if renv.get("container"):
            spec = dict(renv["container"])
            runtime = spec.get("runtime")
            if not runtime:
                import shutil as _sh
                runtime = _sh.which("podman") or _sh.which("docker")
                if runtime is None:
                    raise RuntimeError(
                        "runtime_env['container'] requires podman or "
                        "docker on this node (or an explicit 'runtime' "
                        "binary)")
                spec["runtime"] = runtime
            import ray_tpu
            spec["pkg_root"] = os.path.dirname(
                os.path.dirname(os.path.abspath(ray_tpu.__file__)))
            env_extra["RAY_TPU_WORKER_CONTAINER"] = json.dumps(spec)
        if py_paths:
            existing = env_extra.get("PYTHONPATH",
                                     os.environ.get("PYTHONPATH", ""))
            env_extra["PYTHONPATH"] = os.pathsep.join(
                py_paths + ([existing] if existing else []))
        return env_extra, cwd
