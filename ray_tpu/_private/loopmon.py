"""Per-event-loop busy-fraction probes.

Each daemon event loop (the main loop and every I/O shard thread) runs a
small self-rescheduling callback that samples its OWN thread's CPU time
(`time.thread_time`) against wall time.  The ratio — CPU seconds burned
per wall second by the thread that runs the loop — is the "loop busy"
gauge: ~1.0 means the loop is saturated on one core (the condition the
I/O sharding exists to relieve), ~0.0 means idle.  Exported as
`ray_tpu_daemon_loop_busy_ratio{daemon=...,loop=main|shard<i>}` through
the unified metrics export and shown in `ray_tpu summary`, so
single-core daemon saturation is diagnosable from the gauges instead of
inferred from host CPU.

Thread model: each probe writes only its own label's slot; `snapshot()`
reads the dict from any thread (GIL-consistent; values are immutable
tuples).  A stale entry is NOT dropped: a loop whose probe stopped
ticking while its thread is still alive is a WEDGED loop — exactly the
condition the diagnosis watchdogs alarm on — so `snapshot()` keeps the
label (frozen ratio) and `snapshot_full()` reports how stale it is
(`stale_s`, exported as `ray_tpu_daemon_loop_stale_seconds`) plus the
loop thread's ident so a sibling thread can dump its stack.  Entries
only vanish when the loop CLOSES (clean shutdown).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

DEFAULT_PERIOD_S = 0.5

# label -> (busy_ratio in [0,1], monotonic stamp of the sample,
#           ident of the thread running the loop)
_RATIOS: Dict[str, tuple] = {}


def install(label: str, loop=None, period: float = DEFAULT_PERIOD_S) -> None:
    """Start a busy probe for `loop` under `label`.  Must be called from
    the thread that runs (or will run) the loop, BEFORE or WHILE it runs;
    the first ratio appears after one period.  Idempotent per label
    (reinstalling restarts the sampling baseline)."""
    import asyncio
    if loop is None:
        loop = asyncio.get_event_loop()
    state = {"cpu": None, "wall": None}

    def _tick():
        if loop.is_closed():
            _RATIOS.pop(label, None)
            return
        cpu, wall = time.thread_time(), time.monotonic()
        if state["cpu"] is not None:
            dw = wall - state["wall"]
            if dw > 0:
                _RATIOS[label] = (min(1.0, max(0.0, (cpu - state["cpu"])
                                               / dw)), wall,
                                  threading.get_ident())
        state["cpu"], state["wall"] = cpu, wall
        loop.call_later(period, _tick)

    loop.call_soon(_tick)


def snapshot() -> Dict[str, float]:
    """Last-known busy ratios by label.  Stale entries are KEPT (frozen
    at their last reading) — a wedged-but-alive loop must stay visible in
    the gauges; pair with `snapshot_full()` / the stale-seconds gauge to
    tell frozen from fresh."""
    return {label: entry[0] for label, entry in list(_RATIOS.items())}


def snapshot_full() -> Dict[str, dict]:
    """Per-label probe state for the diagnosis plane:

    ``{"ratio", "stale_s", "thread_ident", "alive"}``

    `stale_s` is the age of the last probe tick — at most one probe
    period (~0.5s) for a healthy loop, growing unboundedly once the loop
    stops servicing callbacks.  `alive` is whether the loop's thread
    still exists: stale+alive = wedged, stale+dead = stopped without
    closing its loop."""
    now = time.monotonic()
    live = {t.ident for t in threading.enumerate()}
    out: Dict[str, dict] = {}
    for label, entry in list(_RATIOS.items()):
        ratio, ts = entry[0], entry[1]
        ident = entry[2] if len(entry) > 2 else None
        out[label] = {
            "ratio": ratio,
            "stale_s": now - ts,
            "thread_ident": ident,
            "alive": ident in live if ident is not None else None,
        }
    return out


def busy(label: str) -> Optional[float]:
    v = snapshot().get(label)
    return v
