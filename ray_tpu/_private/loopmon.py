"""Per-event-loop busy-fraction probes.

Each daemon event loop (the main loop and every I/O shard thread) runs a
small self-rescheduling callback that samples its OWN thread's CPU time
(`time.thread_time`) against wall time.  The ratio — CPU seconds burned
per wall second by the thread that runs the loop — is the "loop busy"
gauge: ~1.0 means the loop is saturated on one core (the condition the
I/O sharding exists to relieve), ~0.0 means idle.  Exported as
`ray_tpu_daemon_loop_busy_ratio{daemon=...,loop=main|shard<i>}` through
the unified metrics export and shown in `ray_tpu summary`, so
single-core daemon saturation is diagnosable from the gauges instead of
inferred from host CPU.

Thread model: each probe writes only its own label's slot; `snapshot()`
reads the dict from any thread (GIL-consistent; values are immutable
tuples).  Stale entries (a stopped shard) age out of snapshots.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

DEFAULT_PERIOD_S = 0.5

# label -> (busy_ratio in [0,1], monotonic stamp of the sample)
_RATIOS: Dict[str, tuple] = {}


def install(label: str, loop=None, period: float = DEFAULT_PERIOD_S) -> None:
    """Start a busy probe for `loop` under `label`.  Must be called from
    the thread that runs (or will run) the loop, BEFORE or WHILE it runs;
    the first ratio appears after one period.  Idempotent per label
    (reinstalling restarts the sampling baseline)."""
    import asyncio
    if loop is None:
        loop = asyncio.get_event_loop()
    state = {"cpu": None, "wall": None}

    def _tick():
        if loop.is_closed():
            _RATIOS.pop(label, None)
            return
        cpu, wall = time.thread_time(), time.monotonic()
        if state["cpu"] is not None:
            dw = wall - state["wall"]
            if dw > 0:
                _RATIOS[label] = (min(1.0, max(0.0, (cpu - state["cpu"])
                                               / dw)), wall)
        state["cpu"], state["wall"] = cpu, wall
        loop.call_later(period, _tick)

    loop.call_soon(_tick)


def snapshot(max_age_s: float = 10.0) -> Dict[str, float]:
    """Fresh busy ratios by label.  Entries older than `max_age_s`
    (stopped loop, wedged thread) are dropped from the view — a frozen
    reading must not masquerade as a live gauge."""
    now = time.monotonic()
    return {label: ratio for label, (ratio, ts) in list(_RATIOS.items())
            if now - ts <= max_age_s}


def busy(label: str) -> Optional[float]:
    v = snapshot().get(label)
    return v
