"""Asyncio RPC: self-delimiting msgpack frames over TCP/unix sockets.

Control-plane transport equivalent of the reference's gRPC layer (reference:
src/ray/rpc/grpc_server.h, retryable_grpc_client.h). gRPC is deliberately not
used for the Python control plane: a lean msgpack framing gives ~5x lower
per-call overhead for the small messages that dominate (task pushes, leases,
heartbeats), which is what lets the task hot loop beat the reference's
microbenchmark numbers. Retry-with-backoff mirrors RetryableGrpcClient;
deterministic fault injection mirrors rpc_chaos.cc
(RAY_testing_rpc_failure="Method=N:req%:resp%").

Wire format: a raw stream of msgpack objects (msgpack is self-delimiting, so
no length prefix). Receive framing + decode run entirely inside msgpack's C
streaming Unpacker fed from an asyncio.Protocol — no StreamReader, no
per-frame await, no Python slicing: measured 1.4-1.7x the calls/s of the
previous length-prefixed StreamReader loop between single-core processes.

Request:  [msg_id, method: str, payload]     (msg_id == 0 -> one-way notify)
          [msg_id, method: str, payload, deadline]   (deadline-carrying)
Response: [msg_id, status: 0|1, result_or_error]

End-to-end deadlines (reference: gRPC deadline propagation; Dean &
Barroso, "The Tail at Scale"): a call issued with deadline=<abs wall
clock> ships it as a 4th frame element.  The receiver refuses to
dispatch an already-expired request (typed "DeadlineExceededError: ..."
error reply) and exposes the deadline to the handler via
current_handler_deadline(), so nested hops inherit the REMAINING budget
instead of stacking fresh per-hop constants.  Caller-side expiry raises
ray_tpu.exceptions.DeadlineExceededError.  Unary calls with no explicit
timeout pick up the process default installed by
set_default_call_timeout() (config control_call_timeout_s) so a
half-open gray connection can never hang a caller forever; call sites
that legitimately block (actor pushes, stream backpressure) opt out
with timeout=0.

Raw out-of-band payloads (reference: object_manager's chunked push carries
object bytes outside the protobuf control messages): bulk bytes skip msgpack
entirely in both directions.  A small header frame

    [0, "__raw__", [rid, nbytes]]

announces that the next `nbytes` on the stream are raw payload for request
`rid`.  The sender hands the payload's memoryviews straight to the transport
(no pack, no join); the receiver scatters the bytes directly into a
caller-provided destination buffer (e.g. a shm-arena create_buffer view or a
spill file) registered via `call_raw`, or collects them for a plain `call`
/ server-side `take_raw`.  This removes every user-space copy except the one
memcpy into the destination.

Native framer (config `rpc_native_framer`, default on): the per-byte
work of this module's hot loops — frame boundary detection, raw-header
parsing, chunk scatter/gather, and syscall batching — runs in the
`_rpcframe.so` C extension when it loads (src/rpcframe, built like the
shm store).  Receive side: a streaming boundary scanner splits each
socket chunk into control spans (still decoded by msgpack's C Unpacker)
and raw payload spans with NO Unpacker reset per raw header, and once a
large raw payload's destination is a writable buffer (a shm arena
region), the connection temporarily takes over the socket —
`pause_reading()` + `add_reader` — and `recv()`s the remaining payload
DIRECTLY into the arena, eliminating the per-read bytes allocation and
Python scatter entirely.  Send side: a frame wave (or raw header +
arena payload views) leaves in one looping `writev` — one syscall per
wave instead of one per frame, zero join copies — and any
EAGAIN-unsent tail is handed back to the transport so the existing
high-watermark backpressure (pause_writing/drain) still governs.  The
pure-Python path remains byte-compatible on the wire and is selected
per process by config/env, or automatically when the extension cannot
load; link_chaos composes with both framers: plans are computed in
Python at the same `_tx`/`_data_received` seam, chaos-delayed bytes
still flow through the native scanner, and the recv takeover simply
disengages on links with inbound chaos rules (delayed delivery
requires buffering by definition).  Per-connection `io_stats` counts
frames, syscalls and takeovers so tests can pin the syscall budget
(one submit_batch wave <= 2 transport submissions).

Daemon I/O shards (config `daemon_io_shards`, default min(4, cores); 0
restores the single-loop mode): a daemon RpcServer constructed with an
`IoShardPool` moves every ACCEPTED connection off the daemon's main
loop onto one of N per-shard event-loop threads.  The whole wire path
— framing, msgpack codec, native-framer recv takeover and vectored
writev — runs on the owning shard, so control-plane byte work scales
with cores instead of serializing on the daemon's one loop.  Handlers
that only touch the arena / per-connection I/O (declared in the
server's `shard_handlers`) run entirely on their shard; every other
request hops to the daemon's main loop through a per-shard
`ShardRouter` that batches a ready-wave of requests into ONE
`call_soon_threadsafe` crossing (never one per frame), preserving
arrival order.  Replies hop back through the cross-thread seam built
into `_maybe_reply`, and the public Connection API (`call`, `notify`,
`close`, ...) transparently bridges to the owning loop when invoked
from a foreign thread, so daemon code may keep references to inbound
connections (pubsub subscribers, registered workers) and use them from
the main loop unchanged.  The wire format is IDENTICAL in both modes —
mixed sharded/unsharded clusters interoperate freely (reference: the
Ray GCS/raylet run their gRPC services on dedicated C++ thread pools
while the application state stays single-threaded behind a post queue).

Authentication (reference: src/ray/rpc/authentication/
authentication_token_validator.cc): when a server is constructed with
auth_token=..., the first frame on every inbound connection must be the
one-way handshake [0, "__auth__", token]; anything else -- or a wrong token
-- aborts the connection before any handler runs. Clients send the handshake
as their first frame after connect. Comparison is constant-time.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import hmac
import logging
import os
import random
import sys
import time
import weakref
from typing import Any, Awaitable, Callable, Dict, Optional

import threading

import msgpack

logger = logging.getLogger(__name__)

MAX_FRAME = 1 << 31
# An unauthenticated peer's entire stream budget: the auth handshake frame
# is <100 bytes, so anything past this is hostile or misdirected traffic.
PREAUTH_MAX_BYTES = 64 << 10


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """Handler raised on the far side; message carries the remote traceback."""


class ConnectionLost(RpcError):
    pass


class AuthError(RpcError):
    """Peer rejected (or never sent) the auth handshake."""


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------
# Handler-scope deadline: set for the duration of a deadline-carrying
# request's dispatch so the handler (and anything it calls synchronously
# on the same context) can bound its own nested work by the REMAINING
# budget rather than a fresh constant.
_handler_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "rpc_handler_deadline", default=None)

# Process default for unary calls issued with timeout=None (installed
# from config control_call_timeout_s by every daemon/driver main).
# None = no default (bare library use keeps today's wait-forever).
_default_call_timeout: Optional[float] = None


def set_default_call_timeout(seconds: Optional[float]) -> None:
    """Install the default timeout applied to call()/call_raw() when the
    call site passes timeout=None.  timeout=0 at a call site always
    opts out (streaming-ish calls that legitimately block)."""
    global _default_call_timeout
    _default_call_timeout = seconds if seconds else None


def current_handler_deadline() -> Optional[float]:
    """Absolute wall-clock deadline of the request currently being
    handled, or None."""
    return _handler_deadline.get()


# Tolerance when a RECEIVER judges a remote absolute deadline against
# its own wall clock: refuse/abort only when expired beyond this slack,
# because the two clocks are different hosts' (NTP keeps them close, but
# a skewed-forward receiver would otherwise spuriously refuse EVERY
# deadline-carrying request whose budget is shorter than the skew).
# The owner-side watchdog — one clock, skew-free — stays the
# authoritative enforcement of the user-visible bound.
DEADLINE_SKEW_SLACK_S = 2.0


def deadline_exceeded(msg: str) -> Exception:
    # Lazy import: this transport module must stay importable on its own
    # (daemon processes import it before the package surface).
    from ..exceptions import DeadlineExceededError
    return DeadlineExceededError(msg)


# Deterministic-per-process jitter for reconnect/retry backoff: seeded by
# pid so one process replays identically, but a fleet reconnecting after
# a GCS restart or netsplit heal de-synchronizes instead of thundering
# in lockstep (each process computes different delays).
_jitter_rng = random.Random(os.getpid() ^ 0x5EED)
os.register_at_fork(  # zygote-forked workers inherit the module-import
    after_in_child=lambda: _jitter_rng.seed(os.getpid() ^ 0x5EED))
#   RNG state — without a reseed every worker on a node would compute
#   IDENTICAL "jitter" and redial in lockstep after a heal.


def _backoff_delay(attempt: int, retry_delay: float,
                   cap: float = 2.0) -> float:
    """Exponential backoff with +/-50% jitter: base * 1.5^attempt capped,
    scaled by uniform [0.5, 1.5)."""
    base = min(retry_delay * (1.5 ** attempt), cap)
    return base * (0.5 + _jitter_rng.random())


# ---------------------------------------------------------------------------
# Copy audit (transfer-path side of serialization.copied_part_bytes):
# every deliberate per-chunk byte materialization on the data plane notes
# itself here, so tests can PIN copies-per-byte for pull / serve paths —
# a regression reintroducing an intermediate bytes() per chunk shows up
# as a counter delta, not a silent throughput loss.  Lives in this module
# (not serialization) because the transport layer and the agent must stay
# importable without cloudpickle.
# ---------------------------------------------------------------------------
COPY_AUDIT: Dict[str, int] = {}
_COPY_AUDIT_LOCK = threading.Lock()


def note_copied_bytes(tag: str, nbytes: int) -> None:
    """Record `nbytes` deliberately materialized (copied) on a transfer
    path.  Tags: serve_partial_chunk (swarm mid-pull serves — 1 copy per
    byte by design: the unsealed buffer's lifetime belongs to the pull),
    serve_legacy_chunk / pull_legacy_chunk (non-raw peers),
    pull_hedge_staging (backup attempt landed in its private buffer).
    Locked: serve paths run on daemon I/O shard threads too, and a
    copy-audit counter the tests PIN must stay exact."""
    with _COPY_AUDIT_LOCK:
        COPY_AUDIT[tag] = COPY_AUDIT.get(tag, 0) + nbytes


def copy_audit_snapshot() -> Dict[str, int]:
    return dict(COPY_AUDIT)


# ---------------------------------------------------------------------------
# Process-wide I/O accounting: per-connection io_stats roll up here so the
# unified metrics export (agent heartbeat / core-worker telemetry tick) can
# ship ONE syscall/frame/byte series per process instead of a per-socket
# cardinality explosion.  Live connections are enumerated via a WeakSet;
# closed connections fold their final counters into _IO_RETIRED at teardown
# so totals stay monotonic across reconnects.
# ---------------------------------------------------------------------------
_LIVE_CONNS: "weakref.WeakSet" = weakref.WeakSet()
_IO_RETIRED: Dict[str, int] = {}
# Connections live and die on daemon I/O shard threads too: the WeakSet
# and the retired fold must not race the main-thread metrics snapshot.
_IO_LOCK = threading.Lock()


def io_stats_snapshot() -> Dict[str, int]:
    """Aggregate io_stats across every connection this process has ever
    opened (live + retired).  Monotonic per key — safe to export as
    counters."""
    with _IO_LOCK:
        out = dict(_IO_RETIRED)
        out.setdefault("connections", 0)
        conns = list(_LIVE_CONNS)
    for conn in conns:
        st = getattr(conn, "io_stats", None)
        if not st:
            continue
        for k, v in list(st.items()):
            out[k] = out.get(k, 0) + v
        out["connections"] += 1
    return out


_BG_TASKS: set = set()


def spawn(coro) -> asyncio.Task:
    """ensure_future with a strong reference held until completion.

    The event loop only weakly references its tasks; a bare ensure_future
    whose Task object isn't stored can be garbage-collected mid-execution,
    silently dropping the work (dispatches, pushes, registrations)."""
    t = asyncio.ensure_future(coro)
    _BG_TASKS.add(t)
    t.add_done_callback(_BG_TASKS.discard)
    return t


async def gather_windowed(fetch_one, positions, window: int) -> list:
    """Run fetch_one(pos) for every position with at most `window` in
    flight, using a FIXED pool of `window` worker tasks draining a shared
    iterator (a 100 GiB pull is ~12k chunks — task-per-chunk would park
    thousands of Task objects to run 8 at a time).  On the first hard
    failure the stragglers are cancelled and awaited (so no orphan task
    keeps streaming into an abandoned buffer) before re-raising.  The
    shared skeleton of every pipelined chunk fetch (agent pulls,
    spilled-object reads).  Results are returned in position order."""
    order = list(positions)
    it = iter(order)
    results: dict = {}

    async def worker():
        for pos in it:          # shared iterator; next() has no await
            results[pos] = await fetch_one(pos)

    tasks = [asyncio.ensure_future(worker())
             for _ in range(max(1, min(window, len(order) or 1)))]
    try:
        await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    return [results[p] for p in order]


def enable_eager_tasks(loop: asyncio.AbstractEventLoop | None = None) -> None:
    """Run new tasks synchronously until their first suspension
    (asyncio.eager_task_factory, 3.12+).  Every runtime loop (driver,
    worker, GCS, agent) opts in: the RPC plane spawns a task per dispatched
    request, and eager execution roughly halves per-call overhead — most
    handlers finish without ever suspending, so they never touch the ready
    queue (measured: 6.4k -> 12.2k pipelined calls/s between two
    single-core processes).  No-op before 3.12 (no eager factory) — the
    runtime is correct either way, just slower per call."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is None:
        return
    loop = loop or asyncio.get_event_loop()
    loop.set_task_factory(factory)


# ---------------------------------------------------------------------------
# Chaos (deterministic RPC fault injection)
# ---------------------------------------------------------------------------
class _Chaos:
    """Parses 'Method=N:req%:resp%,Other=...' — each method fails up to N
    times total, split between request-drop (before handler runs) and
    response-drop (after handler runs). Reference: src/ray/rpc/rpc_chaos.cc."""

    def __init__(self, spec: str):
        self.rules: Dict[str, list] = {}
        self._rng = random.Random(0xC0FFEE)
        # Injection decisions can race across daemon I/O shard threads;
        # the budget decrement must stay exact or a "fails at most N
        # times" chaos test goes flaky under sharding.
        self._lock = threading.Lock()
        for part in filter(None, (p.strip() for p in spec.split(","))):
            name, rhs = part.split("=")
            fields = rhs.split(":")
            count = int(fields[0])
            req_p = int(fields[1]) if len(fields) > 1 else 50
            resp_p = int(fields[2]) if len(fields) > 2 else 0
            self.rules[name] = [count, req_p, resp_p]

    def should_fail(self, method: str, phase: str) -> bool:
        rule = self.rules.get(method)
        if not rule or rule[0] <= 0:
            return False
        with self._lock:
            if rule[0] <= 0:
                return False
            p = rule[1] if phase == "req" else rule[2]
            if self._rng.randint(1, 100) <= p:
                rule[0] -= 1
                return True
        return False


_chaos: Optional[_Chaos] = None


def enable_chaos(spec: str):
    global _chaos
    _chaos = _Chaos(spec) if spec else None


# Link-level chaos (config `link_chaos`, parsed/planned by
# chaos.LinkChaos): per-peer delay/jitter/bandwidth/asymmetric blackhole
# applied to this process's RPC byte stream.  None (the default) costs
# one attribute load on the hot paths.
_link_chaos = None


def enable_link_chaos(spec: str, seed: int = 0xC0FFEE):
    global _link_chaos
    if spec:
        from .chaos import LinkChaos
        _link_chaos = LinkChaos(spec, seed=seed)
    else:
        _link_chaos = None


# ---------------------------------------------------------------------------
# Native framer selection (see module docstring).  None = auto: consult
# config `rpc_native_framer` and extension availability lazily at
# connection setup; True/False = explicit process-wide override (tests,
# daemons honoring per-node _system_config).
# ---------------------------------------------------------------------------
_native_framer: Optional[bool] = None

# Raw payloads with at least this many bytes still in flight switch the
# socket into the native recv-into-arena mode; smaller remainders aren't
# worth the reader swap.  Tests lower it to exercise the takeover.
NATIVE_RECV_MIN = 64 * 1024


def enable_native_framer(on: Optional[bool]) -> None:
    """Force the native framer on/off for this process (None restores
    auto).  'On' still degrades to pure Python when the extension
    cannot load — never an error."""
    global _native_framer
    _native_framer = on


def _native_available() -> bool:
    try:
        from . import rpcframe
        return rpcframe.available()
    except Exception:       # noqa: BLE001 — transport must never die here
        return False


def _resolve_native() -> bool:
    if _native_framer is not None:
        return _native_framer and _native_available()
    try:
        from .config import get_config
        if not get_config().rpc_native_framer:
            return False
    except Exception:       # config unavailable: bare library use
        pass
    return _native_available()


# ---------------------------------------------------------------------------
# Connection
# ---------------------------------------------------------------------------
def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _unpack(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


# Sentinel a fast handler returns to route the request through the normal
# coroutine handler instead (slow/conditional branch).
FAST_FALLBACK = object()


def _nbytes(b) -> int:
    # Same contract as serialization.part_nbytes — kept separate so this
    # transport module stays importable without cloudpickle (GCS/agent
    # daemons) and serialization stays importable without msgpack.
    return b.nbytes if isinstance(b, memoryview) else len(b)


class RawPayload:
    """Handler return value: reply with a raw out-of-band payload frame.

    `buffers` is a list of bytes-like chunks sent back-to-back after the
    header frame — handed to the transport as-is (zero user-space copies).
    `release` (optional) runs once the bytes can no longer be read by the
    transport — the safe point to drop shm pins.  On Python <=3.11 the
    selector transport copies any kernel-backpressured tail into its own
    bytearray, so that point is right after write(); on 3.12+ the
    transport buffers unsent data BY REFERENCE (zero-copy writes,
    gh-91166), so release is deferred until the write buffer has fully
    flushed (see Connection.send_raw)."""

    __slots__ = ("buffers", "nbytes", "release")

    def __init__(self, buffers, release: Callable | None = None):
        self.buffers = list(buffers)
        self.nbytes = sum(_nbytes(b) for b in self.buffers)
        self.release = release

    def close(self):
        rel, self.release = self.release, None
        if rel is not None:
            try:
                rel()
            except Exception:
                logger.exception("RawPayload release failed")

# Process-wide default auth token (reference: authentication_token_loader.cc
# reads RAY_AUTH_TOKEN/token file once per process). Servers require it and
# clients send it unless a call site overrides explicitly; DEFAULT_TOKEN as
# a parameter default means "use the process default", None means "no auth".
DEFAULT_TOKEN = object()
_default_token: Optional[str] = None


def set_default_token(token: Optional[str]) -> None:
    """Install the session auth token for every server/connect in this
    process that doesn't override it. Empty/None disables."""
    global _default_token
    _default_token = token or None


def _resolve_token(token) -> Optional[str]:
    return _default_token if token is DEFAULT_TOKEN else token


class _WireProtocol(asyncio.Protocol):
    """Thin adapter: the event loop calls here, the Connection does the work."""

    __slots__ = ("conn",)

    def __init__(self, conn: "Connection"):
        self.conn = conn

    def connection_made(self, transport):
        self.conn._connection_made(transport)

    def data_received(self, data):
        self.conn._data_received(data)

    def eof_received(self):
        return False  # close the transport; connection_lost follows

    def connection_lost(self, exc):
        self.conn._teardown()

    def pause_writing(self):
        self.conn._paused = True

    def resume_writing(self):
        self.conn._resume_writing()


class Connection:
    """A bidirectional pipelined RPC connection. Both sides may issue calls
    (needed for worker↔agent and pubsub push)."""

    def __init__(self, handlers: Dict[str, Callable] | None = None,
                 name: str = "", on_close: Callable | None = None,
                 fast_handlers: Dict[str, Callable] | None = None,
                 auth_token: str | None = None,
                 send_token: str | None = None,
                 on_connect: Callable | None = None,
                 native: bool | None = None):
        self.handlers = handlers if handlers is not None else {}
        # Fast handlers: SYNC callables (conn, payload) -> asyncio.Future
        # | FAST_FALLBACK | immediate result. They run inline in the recv
        # path — no Task per request — and the reply is sent from a
        # done-callback when a Future is returned.  Meant for enqueue-style
        # handlers (push_task/push_actor_task) whose coroutine bodies just
        # park on an internal queue: under fan-out load the Task-per-call
        # dispatch was a measurable share of the worker loop's CPU.
        self.fast_handlers = fast_handlers or {}
        self.name = name
        self.on_close = on_close
        self.on_connect = on_connect
        # Server side: require this token before processing any frame.
        self._auth_token = auth_token
        self._authed = auth_token is None
        self._preauth_bytes = 0
        # Client side: handshake to emit as the very first frame.
        self._send_token = send_token
        self.transport: asyncio.Transport | None = None
        self._next_id = 1
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._paused = False
        self._drain_waiters: list = []
        # All receive framing + msgpack decode happens inside this C
        # streaming unpacker; data_received feeds it raw socket bytes.
        self._unpacker = msgpack.Unpacker(
            raw=False, strict_map_key=False, max_buffer_size=MAX_FRAME)
        # Bytes fed to the CURRENT unpacker (reset with it): `fed - tell()`
        # is how many buffered-but-unparsed bytes follow the last decoded
        # frame — the raw-payload prefix when that frame is a raw header.
        self._fed = 0
        # Raw out-of-band payload state (see module docstring):
        #   _raw_sinks: rid -> destination registered by call_raw
        #   _raw_cur:   [rid, remaining, sink, written] while receiving
        #   _raw_takers: rid -> [collector, future] (server-side take_raw)
        #   _raw_orphans: rid -> [collector, done] payloads that arrived
        #       before (or without) a taker — bounded, oldest evicted
        self._raw_sinks: Dict[int, Any] = {}
        self._raw_cur: list | None = None
        self._raw_takers: Dict[int, list] = {}
        self._raw_orphans: Dict[int, list] = {}
        self._raw_evicted = collections.deque(maxlen=64)
        # Frame coalescing: frames queued in one loop tick go out as ONE
        # transport.write (one syscall) — under task fan-out the loop was
        # spending ~3/4 of its samples in per-frame socket sends.
        self._wbuf: list = []
        self._flush_scheduled = False
        # Reply coalescing: response frames queued in one loop tick leave
        # as ONE __batch_resp__ frame — one msgpack pack here and one
        # frame decode on the peer instead of K of each (a chunk of K
        # actor calls resolves K replies in the same tick).
        self._resp_buf: list = []
        self._resp_scheduled = False
        # Link-chaos state: ordered delayed-delivery queues
        # [(bytes, due_monotonic), ...] per direction, each drained by
        # one task — delays pipeline (every unit waits its own latency)
        # but never reorder the stream.  Allocated lazily on the first
        # chaos-planned byte so production connections stay
        # allocation-free here.
        self._tx_q: Any = None
        self._tx_task: Optional[asyncio.Task] = None
        self._rx_q: Any = None
        self._rx_task: Optional[asyncio.Task] = None
        self._link_descr: Optional[str] = None
        # Native framer state (see module docstring): resolved at
        # _connection_made (transport/fd known).  `native` here is a
        # per-connection override for tests/mixed-mode harnesses; None
        # follows the process-wide config.
        self._native_pref = native
        self._use_native = False
        self._framer = None             # rpcframe.Scanner
        self._sock_fd = -1
        self._loop = None
        self._native_rx = False         # recv-takeover engaged
        self._rx_export = None          # ctypes export pinning the sink
        self._rx_addr = 0
        # dup(2) of the socket fd, created lazily at the first takeover:
        # asyncio refuses add_reader on a transport-owned fd, but a dup
        # shares the same socket and passes the ownership check.
        self._dup_fd = -1
        # Transport I/O accounting, both framers: 'syscalls' counts
        # direct socket submissions (writev calls + transport.writes);
        # a transport.write may buffer rather than send, so the number
        # is an upper bound on send syscalls — exactly what the
        # "<= 2 per wave" budget needs.
        self.io_stats = {"tx_syscalls": 0, "tx_frames": 0,
                         "tx_writev": 0, "tx_bytes": 0,
                         "rx_native_bytes": 0, "rx_takeovers": 0}
        # Daemon I/O sharding: set by a sharded RpcServer's accept loop.
        # Non-None routes every request not in the router's shard-local
        # set to the daemon's main loop (batched, order-preserving).
        self._router: Optional["ShardRouter"] = None
        with _IO_LOCK:
            _LIVE_CONNS.add(self)

    @property
    def closed(self):
        return self._closed

    # Back-compat surface for callers that reached into .writer.transport.
    @property
    def writer(self):
        return self

    # ------------------------------------------- cross-thread seam --
    # A connection is OWNED by the loop that created its transport
    # (self._loop) — for daemon-sharded connections that is an I/O shard
    # thread, while daemon code holding a reference (pubsub subscribers,
    # registered workers, hop-dispatched handlers) runs on the main
    # loop.  Transports and the framing state are not thread-safe, so
    # every public entry point bridges to the owning loop when invoked
    # from a foreign thread.  Single-loop processes never take these
    # branches (the owner check is one get_running_loop + identity
    # compare).

    def _on_owner_loop(self) -> bool:
        try:
            return asyncio.get_running_loop() is self._loop
        except RuntimeError:
            return False

    async def _bridge(self, coro) -> Any:
        """Run `coro` on the owning loop; await its outcome here."""
        try:
            cf = asyncio.run_coroutine_threadsafe(coro, self._loop)
        except RuntimeError:
            # Owning loop already closed (daemon teardown): same typed
            # outcome a same-loop caller gets from a dead connection.
            coro.close()
            raise ConnectionLost(
                f"connection {self.name} loop closed") from None
        try:
            return await asyncio.wrap_future(cf)
        finally:
            if not cf.done():
                cf.cancel()

    def abort(self):
        if self._loop is not None and not self._on_owner_loop():
            try:
                self._loop.call_soon_threadsafe(self._abort_local)
            except RuntimeError:
                pass        # owner loop gone: nothing left to abort
            return
        self._abort_local()

    def _abort_local(self):
        if self.transport is not None:
            self.transport.abort()

    # ---------------------------------------------------------- wire events
    def _connection_made(self, transport):
        self.transport = transport
        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            self._loop = asyncio.get_event_loop()
        use_native = self._native_pref if self._native_pref is not None \
            else _resolve_native()
        if use_native and _native_available():
            try:
                sock = transport.get_extra_info("socket")
                fd = sock.fileno() if sock is not None else -1
                if fd >= 0:
                    from . import rpcframe
                    self._framer = rpcframe.Scanner()
                    self._sock_fd = fd
                    self._use_native = True
            except Exception:
                logger.warning("native framer setup failed on %s; using "
                               "pure-Python framing", self.name,
                               exc_info=True)
                self._framer = None
                self._use_native = False
        if self._send_token is not None:
            # First frame on the wire, ahead of any queued call: the write
            # path appends in order, so this is guaranteed to arrive first.
            self._send_frame([0, "__auth__", self._send_token])
        if self.on_connect is not None:
            try:
                self.on_connect(self)
            except Exception:
                logger.exception("on_connect callback failed")

    def _resume_writing(self):
        self._paused = False
        waiters, self._drain_waiters = self._drain_waiters, []
        for w in waiters:
            if not w.done():
                w.set_result(None)

    # ------------------------------------------------------- link chaos --
    def _link_desc(self) -> str:
        """'<conn name>|<peer host:port>' — what link_chaos rule `match`
        filters run against."""
        d = self._link_descr
        if d is None:
            peer = ""
            if self.transport is not None:
                try:
                    info = self.transport.get_extra_info("peername")
                    if isinstance(info, tuple) and len(info) >= 2:
                        peer = f"{info[0]}:{info[1]}"
                    elif info:
                        peer = str(info)
                except Exception:
                    pass
            d = self._link_descr = f"{self.name}|{peer}"
        return d

    def _tx(self, data) -> None:
        """All outbound bytes funnel here: direct transport write when no
        link chaos is enabled, else drop/delay/throttle per the plan.
        Each unit handed in is a complete frame (or raw-payload segment
        covered by a group plan), so dropping a unit never desyncs the
        peer's frame parser mid-message."""
        lc = _link_chaos
        if lc is None:
            st = self.io_stats
            st["tx_syscalls"] += 1
            st["tx_bytes"] += _nbytes(data)
            try:
                self.transport.write(data)
            except (ConnectionError, OSError):
                self._teardown()
            return
        drop, delay = lc.plan("out", self._link_desc(), _nbytes(data))
        self._tx_enqueue(data, drop, delay)

    def _tx_enqueue(self, data, drop: bool, delay: float) -> None:
        if drop:
            return
        if delay <= 0 and not self._tx_q:
            try:
                self.transport.write(data)
            except (ConnectionError, OSError):
                self._teardown()
            return
        # Due times are clamped monotonic so jitter can't reorder bytes.
        if self._tx_q is None:
            self._tx_q = collections.deque()
        due = time.monotonic() + delay
        if self._tx_q and due < self._tx_q[-1][1]:
            due = self._tx_q[-1][1]
        self._tx_q.append((bytes(data) if isinstance(data, memoryview)
                           else data, due))
        if self._tx_task is None:
            self._tx_task = spawn(self._chaos_drain("_tx_q", "_tx_task",
                                                    self._tx_emit))

    def _tx_emit(self, data) -> None:
        try:
            self.transport.write(data)
        except (ConnectionError, OSError):
            self._teardown()

    async def _chaos_drain(self, qattr: str, taskattr: str, emit) -> None:
        q = getattr(self, qattr)
        try:
            while q:
                data, due = q[0]
                dt = due - time.monotonic()
                if dt > 0:
                    await asyncio.sleep(dt)
                if self._closed or self.transport is None:
                    q.clear()
                    return
                q.popleft()
                emit(data)
        finally:
            setattr(self, taskattr, None)
            if q and not self._closed:
                setattr(self, taskattr,
                        spawn(self._chaos_drain(qattr, taskattr, emit)))

    def _rx_emit(self, data) -> None:
        # _rx_process handles its own malformed-stream aborts.
        self._rx_process(data)

    def _data_received(self, data):
        lc = _link_chaos
        if lc is not None:
            drop, delay = lc.plan("in", self._link_desc(), len(data))
            if drop:
                # Blackholed inbound direction: the bytes vanish but the
                # TCP session stays up — the asymmetric-partition shape.
                # (A drop window that ENDS mid-message resumes the stream
                # mid-frame; the parser then aborts the connection, which
                # is the same reset a healing middlebox produces.)
                return
            if delay > 0 or self._rx_q:
                if self._rx_q is None:
                    self._rx_q = collections.deque()
                due = time.monotonic() + delay
                if self._rx_q and due < self._rx_q[-1][1]:
                    due = self._rx_q[-1][1]
                self._rx_q.append((bytes(data), due))
                if self._rx_task is None:
                    self._rx_task = spawn(self._chaos_drain(
                        "_rx_q", "_rx_task", self._rx_emit))
                return
        self._rx_process(data)

    def _rx_process(self, data):
        try:
            if self._use_native:
                self._ingest_native(data)
            else:
                self._ingest(memoryview(data))
        except Exception:
            # Malformed stream (bad msgpack, oversized buffer, raw-frame
            # desync): drop peer.
            logger.warning("malformed stream on %s; closing", self.name,
                           exc_info=True)
            self.abort()
            return
        if not self._authed:
            # Still unauthenticated AFTER processing this chunk (the check
            # runs post-feed so a handshake coalesced with a large first
            # request in one chunk authenticates before the cap applies):
            # budget the stream so a hostile peer can't make us buffer up
            # to MAX_FRAME (2 GiB) of an incomplete frame.
            self._preauth_bytes += len(data)
            if self._preauth_bytes > PREAUTH_MAX_BYTES:
                logger.warning("pre-auth stream exceeded %d bytes on %s; "
                               "dropping", PREAUTH_MAX_BYTES, self.name)
                self.abort()

    def _ingest(self, mv: memoryview) -> None:
        """Demultiplex the stream: msgpack frames through the C unpacker,
        raw payloads (announced by a `__raw__` header frame) scattered
        straight into their destination without touching the unpacker."""
        while True:
            raw = self._raw_cur
            if raw is not None:
                take = min(raw[1], mv.nbytes)
                if take:
                    self._raw_deliver(mv[:take])
                    mv = mv[take:]
                if self._raw_cur is not None and self._raw_cur[1] > 0:
                    return                      # payload continues next chunk
                self._finish_raw()
                if not mv.nbytes:
                    return
                continue
            if not mv.nbytes:
                return
            self._unpacker.feed(mv)
            self._fed += mv.nbytes
            hit_raw = False
            for msg in self._unpacker:
                if (isinstance(msg, (list, tuple)) and len(msg) == 3
                        and msg[0] == 0 and msg[1] == "__raw__"):
                    if not self._authed:
                        raise RpcError("raw frame before auth handshake")
                    rid, nbytes = msg[2]
                    if not isinstance(nbytes, int) or nbytes < 0 \
                            or nbytes > MAX_FRAME:
                        raise RpcError(f"bad raw frame length {nbytes!r}")
                    # Bytes the unpacker buffered past the header are the
                    # payload prefix.  They can only have arrived in the
                    # chunk that completed the header (every earlier chunk
                    # was drained by its own iteration pass), so they are
                    # a suffix of `mv` — reclaim them and discard the
                    # unpacker's copy by starting a fresh unpacker.
                    leftover = self._fed - self._unpacker.tell()
                    if leftover < 0 or leftover > mv.nbytes:
                        raise RpcError("raw framing desync")
                    self._unpacker = msgpack.Unpacker(
                        raw=False, strict_map_key=False,
                        max_buffer_size=MAX_FRAME)
                    self._fed = 0
                    self._begin_raw(rid, nbytes)
                    mv = mv[mv.nbytes - leftover:]
                    hit_raw = True
                    break
                self._on_msg(msg)
            if not hit_raw:
                return

    def _ingest_native(self, data) -> None:
        """Native-framer ingest: the C scanner splits the chunk into
        control spans (fed to the msgpack decoder), raw headers and
        payload spans — no Unpacker reset per raw header, no tell()
        bookkeeping.  Event semantics mirror _ingest exactly; parity is
        pinned by tests/test_rpc_framer.py."""
        from . import rpcframe
        mv = memoryview(data)
        n = mv.nbytes
        pos = 0
        while pos < n:
            fr = self._framer
            if fr is None or self._closed:
                return      # torn down mid-ingest (a handler's write
            #                 failed): the rest of the chunk is moot
            nev, consumed = fr.scan(data, pos)
            if nev < 0:
                raise RpcError("malformed stream (native framer)")
            evt, eva, evb = fr.evt, fr.eva, fr.evb
            for i in range(nev):
                if self._closed:
                    return  # a dispatched handler tore the conn down
                t = evt[i]
                if t == rpcframe.EV_CTRL:
                    a = pos + eva[i]
                    self._unpacker.feed(mv[a:a + evb[i]])
                    for msg in self._unpacker:
                        self._on_msg(msg)
                elif t == rpcframe.EV_RAW_DATA:
                    a = pos + eva[i]
                    self._raw_deliver(mv[a:a + evb[i]])
                    if self._raw_cur is not None and self._raw_cur[1] == 0:
                        self._finish_raw()
                elif t == rpcframe.EV_RAW_BEGIN:
                    if not self._authed:
                        raise RpcError("raw frame before auth handshake")
                    rid, nbytes = eva[i], evb[i]
                    if nbytes < 0 or nbytes > MAX_FRAME:
                        raise RpcError(f"bad raw frame length {nbytes!r}")
                    self._begin_raw(rid, nbytes)
                    if nbytes == 0:
                        self._finish_raw()
                else:  # EV_STASH_CTRL: header-split bytes reclassified
                    self._unpacker.feed(fr.spill_bytes(eva[i], evb[i]))
                    for msg in self._unpacker:
                        self._on_msg(msg)
            if consumed == 0:
                raise RpcError("native framer made no progress")
            pos += consumed
        self._maybe_native_recv()

    # ------------------------------------------ native recv takeover --
    def _maybe_native_recv(self) -> None:
        """If a large raw payload is mid-flight into a writable buffer,
        stop routing its bytes through the transport: pause the
        protocol's reading and recv() the remainder straight into the
        destination (the shm arena region) until it completes."""
        raw = self._raw_cur
        if (self._native_rx or raw is None or self._closed
                or raw[1] < NATIVE_RECV_MIN or self._sock_fd < 0):
            return
        sink = raw[2]
        if not (isinstance(sink, memoryview) and not sink.readonly
                and sink.c_contiguous):
            return
        if raw[3] + raw[1] > sink.nbytes:
            # Announced payload exceeds the registered sink: never let a
            # native recv() run past the destination buffer (memory
            # safety — a buggy or hostile peer must not be able to
            # corrupt the heap).  The buffered path handles the
            # overflow: the scatter raises, the sink drops into discard
            # mode, and the caller's future fails typed.
            return
        if self._rx_q:
            return          # chaos-delayed bytes already queued: keep order
        lc = _link_chaos
        if lc is not None and lc.matches_in(self._link_desc()):
            return          # inbound chaos plans need the buffered path
        import ctypes as _ct
        try:
            # Pins the sink's buffer for the takeover's duration; the
            # export is dropped the moment the takeover ends so arena
            # abort/release paths are never blocked by it.
            export = _ct.c_char.from_buffer(sink)
            if self._dup_fd < 0:
                self._dup_fd = os.dup(self._sock_fd)
                os.set_blocking(self._dup_fd, False)
            self.transport.pause_reading()
            self._loop.add_reader(self._dup_fd, self._native_rx_step)
        except Exception:
            # Transport/loop without reader control (or an exotic
            # buffer): the buffered path still works.
            try:
                self.transport.resume_reading()
            except Exception:
                pass
            return
        self._rx_export = export
        self._rx_addr = _ct.addressof(export)
        self._native_rx = True
        self.io_stats["rx_takeovers"] += 1

    def _native_rx_step(self) -> None:
        """Reader callback while a takeover is active: drain the socket
        into the sink until the payload completes or would block."""
        from . import rpcframe
        raw = self._raw_cur
        if (self._closed or raw is None
                or not isinstance(raw[2], memoryview)):
            # Finished/defused (call_raw timeout) under us: hand the
            # stream back to the transport — the Python path discards
            # or completes the remainder with full accounting.
            self._native_rx_end()
            return
        got, state, err, _nsys = rpcframe.recv_into(
            self._dup_fd, self._rx_addr + raw[3], raw[1])
        if got:
            raw[3] += got
            raw[1] -= got
            self.io_stats["rx_native_bytes"] += got
        if raw[1] == 0:
            self._native_rx_end()
            self._finish_raw()
            return
        if state == rpcframe.RECV_EOF or state == rpcframe.RECV_ERROR:
            self._native_rx_end(resume=False)
            self.abort()    # connection_lost -> _teardown, like eof/reset

    def _native_rx_end(self, resume: bool = True) -> None:
        if not self._native_rx:
            return
        self._native_rx = False
        try:
            self._loop.remove_reader(self._dup_fd)
        except Exception:
            pass
        self._rx_export = None
        self._rx_addr = 0
        # Re-sync the scanner: it never saw the bytes recv'd natively.
        raw = self._raw_cur
        if self._framer is not None:
            self._framer.set_raw_remaining(raw[1] if raw is not None
                                           else 0)
        if resume and not self._closed and self.transport is not None:
            try:
                self.transport.resume_reading()
            except Exception:
                pass

    # Orphaned raw payloads kept for a late take_raw (see _begin_raw):
    # bounded by count AND total buffered bytes.  Evicted rids are
    # remembered so a late take_raw errors promptly instead of hanging
    # out its full timeout.
    _MAX_RAW_ORPHANS = 32
    _MAX_RAW_ORPHAN_BYTES = 256 << 20

    def _begin_raw(self, rid: int, nbytes: int) -> None:
        sink = self._raw_sinks.pop(rid, None)
        if sink is None:
            taker = self._raw_takers.get(rid)
            if taker is not None:
                sink = taker[0]                 # collector bytearray
            elif rid > 0:
                if rid in self._pending:
                    sink = bytearray()          # plain call(): collect
                else:
                    # Response whose call was reaped (timeout): nobody
                    # can ever claim it — discard, don't buffer.
                    sink = None
            else:
                # Request-side payload arriving before its take_raw (or
                # a stray): buffer it so the handler can still claim it.
                orphan = [bytearray(), False]
                self._raw_orphans[rid] = orphan
                while (len(self._raw_orphans) > self._MAX_RAW_ORPHANS
                       or sum(len(o[0]) for o in
                              self._raw_orphans.values())
                       > self._MAX_RAW_ORPHAN_BYTES):
                    old = next(iter(self._raw_orphans))
                    if old == rid:
                        break       # never evict the one being received
                    self._raw_orphans.pop(old)
                    self._raw_evicted.append(old)
                sink = orphan[0]
        # [rid, remaining, sink, written, sink_error]
        self._raw_cur = [rid, nbytes, sink, 0, None]

    def _raw_deliver(self, piece: memoryview) -> None:
        raw = self._raw_cur
        sink = raw[2]
        n = piece.nbytes
        try:
            if sink is None:
                pass                            # discard mode
            elif isinstance(sink, bytearray):
                sink += piece
            elif callable(sink):
                sink(piece)
            else:                               # writable buffer: scatter
                sink[raw[3]:raw[3] + n] = piece
        except Exception as e:
            # A broken sink (closed fd, undersized buffer) must not kill
            # the whole connection: drop into discard mode, remember the
            # error so the caller's future fails instead of reporting a
            # phantom success.
            logger.warning("raw sink failed on %s: %r", self.name, e)
            raw[2] = None
            raw[4] = e
        raw[3] += n
        raw[1] -= n

    def _finish_raw(self) -> None:
        rid, _remaining, sink, written, error = self._raw_cur
        self._raw_cur = None
        # Resolve by rid, not by sink identity: a mid-reception sink
        # error replaces the sink with None (discard mode), and the
        # waiter must still learn the outcome rather than hang.
        taker = self._raw_takers.pop(rid, None)
        if taker is not None:
            if not taker[1].done():
                if error is not None:
                    taker[1].set_exception(
                        RpcError(f"raw sink failed: {error!r}"))
                else:
                    taker[1].set_result(bytes(taker[0]))
            return
        orphan = self._raw_orphans.get(rid)
        if orphan is not None:
            if error is not None:
                # A late take_raw must fail fast, not adopt a truncated
                # collector or wait out its timeout.
                self._raw_orphans.pop(rid, None)
                self._raw_evicted.append(rid)
            else:
                orphan[1] = True                # complete; awaiting taker
            return
        fut = self._pending.pop(rid, None)
        if fut is not None and not fut.done():
            if error is not None:
                fut.set_exception(RpcError(f"raw sink failed: {error!r}"))
            else:
                fut.set_result(bytes(sink) if isinstance(sink, bytearray)
                               else written)
            return
        if fut is None and rid < 0 and error is None \
                and isinstance(sink, bytearray):
            # Request-side payload whose taker gave up (take_raw timed
            # out after reception started): keep it as a COMPLETED
            # orphan so a retrying take_raw returns the real bytes
            # instead of hanging on a payload that already arrived.
            self._raw_orphans[rid] = [sink, True]

    async def take_raw(self, rid: int, timeout: float | None = None) -> bytes:
        """Server-side: await the raw payload a peer announced for request
        `rid` (callers put their request's msg_id in the payload so the
        handler knows it).  Returns the collected bytes."""
        if self._loop is not None and not self._on_owner_loop():
            # Hop-dispatched handler on the daemon's main loop: the raw
            # orphan/taker state (and the future _finish_raw resolves)
            # belong to the owning shard loop.
            return await self._bridge(self.take_raw(rid, timeout))
        orphan = self._raw_orphans.pop(rid, None)
        if orphan is not None:
            if orphan[1]:
                return bytes(orphan[0])
            # Mid-reception: adopt the partially-filled collector.
            fut = asyncio.get_running_loop().create_future()
            self._raw_takers[rid] = [orphan[0], fut]
        elif rid in self._raw_evicted:
            raise RpcError(
                f"raw payload {rid} was evicted from the orphan buffer "
                f"before take_raw claimed it")
        else:
            fut = asyncio.get_running_loop().create_future()
            self._raw_takers[rid] = [bytearray(), fut]
        try:
            if timeout:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            taker = self._raw_takers.pop(rid, None)
            cur = self._raw_cur
            if taker is not None and cur is not None and cur[0] == rid \
                    and cur[2] is taker[0]:
                # Timed out mid-reception: re-orphan the partially-filled
                # collector so a retrying take_raw adopts THIS payload
                # instead of racing it with a fresh empty collector
                # (which would resolve to truncated/empty bytes).
                self._raw_orphans[rid] = [taker[0], False]

    # 3.12+ selector transports keep unsent write() data by REFERENCE
    # (zero-copy writes, gh-91166); earlier versions copy the tail into a
    # private bytearray.  Decides when a RawPayload's pins may drop.
    _WRITES_BUFFER_BY_REF = sys.version_info >= (3, 12)

    def send_raw(self, rid: int, payload: RawPayload) -> None:
        """Emit a raw payload frame for `rid`: header + buffers straight to
        the transport, ordered after everything already queued."""
        done = False
        try:
            if self._closed:
                return
            self._flush_resp()
            self._flush_wbuf()
            if self._closed:
                return
            lc = _link_chaos
            if lc is not None:
                # ONE plan for the whole header+payload group: a drop
                # decision that split them would desync the peer's raw
                # framing.  Queued units copy the views (the arena pins
                # can then drop immediately) — chaos-mode-only cost.
                header = _pack([0, "__raw__", [rid, payload.nbytes]])
                drop, delay = lc.plan("out", self._link_desc(),
                                      len(header) + payload.nbytes)
                self._tx_enqueue(header, drop, delay)
                for b in payload.buffers:
                    self._tx_enqueue(bytes(b), drop, delay)
                return
            header = _pack([0, "__raw__", [rid, payload.nbytes]])
            if self._vectored_ok():
                # Native gather path: header + payload views (straight
                # out of the shm arena) leave in one looping writev.
                # Fully written -> the kernel holds copies, so arena
                # pins can drop NOW; a backpressured tail went to the
                # transport and follows the buffer-by-ref rules below.
                if self._tx_vectored([header] + [
                        b for b in payload.buffers if _nbytes(b)]):
                    return          # finally: payload.close()
                if self._closed:
                    return
            else:
                try:
                    self.io_stats["tx_syscalls"] += 1 + len(payload.buffers)
                    self.io_stats["tx_bytes"] += \
                        len(header) + payload.nbytes
                    self.transport.write(header)
                    for b in payload.buffers:
                        self.transport.write(b)
                except (ConnectionError, OSError):
                    self._teardown()
                    return
            if (self._WRITES_BUFFER_BY_REF and payload.release is not None
                    and not self._closed and self.transport is not None
                    and self.transport.get_write_buffer_size() > 0):
                # The transport may still be holding our views: defer the
                # pin drop until the write buffer fully flushes, or the
                # backing arena region could be evicted and rewritten
                # before the kernel reads it.
                done = True
                spawn(self._close_when_flushed(payload))
        finally:
            if not done:
                payload.close()

    async def _close_when_flushed(self, payload: RawPayload) -> None:
        try:
            while not self._closed and self.transport is not None \
                    and self.transport.get_write_buffer_size() > 0:
                await self.drain()          # below high watermark…
                if self._closed or self.transport is None or \
                        self.transport.get_write_buffer_size() == 0:
                    break
                await asyncio.sleep(0.002)  # …then poll down to empty
        finally:
            # Closed/aborted counts too: the bytes will never be read.
            payload.close()

    def _on_msg(self, msg):
        if not isinstance(msg, (list, tuple)) or len(msg) not in (3, 4):
            logger.warning("malformed frame on %s", self.name)
            return
        # 4th element: absolute wall-clock deadline on a request frame.
        mid, a, b = msg[0], msg[1], msg[2]
        dl = msg[3] if len(msg) == 4 else None
        if not self._authed:
            # EVERY frame shape is gated until the handshake lands —
            # response-shaped frames from an unauthenticated peer could
            # otherwise resolve/poison pending futures on this connection.
            if (isinstance(a, str) and a == "__auth__" and
                    isinstance(b, str) and
                    hmac.compare_digest(b, self._auth_token)):
                self._authed = True
            else:
                logger.warning("auth failure on %s (first frame %r); "
                               "dropping connection", self.name, a)
                self.abort()
            return
        if isinstance(a, str):  # request [mid, method, payload]
            if a == "__raw__":
                # A raw header that reached frame dispatch instead of
                # the framer's interception.  Native path: a peer
                # packed the header in a legal-but-NON-minimal msgpack
                # encoding the scanner's byte-exact magic (rpcframe.cc
                # kMagic) didn't claim — the next stream bytes are
                # payload, not frames, so abort typed before the parser
                # desyncs into them.  (The Python framer matches on the
                # DECODED object, so it intercepts non-minimal forms
                # upstream; here it only sees malformed shapes — mid!=0
                # or a deadline-carrying 4-frame — which no peer emits.)
                # Wire invariant: raw headers are packed minimally.
                raise RpcError("unintercepted __raw__ header "
                               "(malformed or non-minimal encoding)")
            if a == "__auth__":
                return  # authed already (or server auth disabled): ignore
            if a == "__batch_resp__":
                # Coalesced responses (see _send_reply): resolve each
                # pending future in arrival order.
                pend = self._pending
                for sub in b:
                    fut = pend.pop(sub[0], None)
                    if fut is not None and not fut.done():
                        if sub[1] == 0:
                            fut.set_result(sub[2])
                        else:
                            fut.set_exception(RemoteError(sub[2]))
                return
            if a == "__batch__":
                # Multi-call frame: K independent requests in one frame
                # (see call_many). Each dispatches separately and replies
                # with its own response frame, so the semantics are
                # identical to K pipelined call()s — only the framing
                # overhead is amortized.
                rt = self._router
                fhs = self.fast_handlers
                for sub in b:
                    if rt is not None:
                        lh = rt.local.get(sub[1])
                        if lh is None:
                            rt.submit(self, sub[0], sub[1], sub[2])
                        else:
                            self._dispatch_fast(sub[0], sub[1], lh,
                                                sub[2], fallback=rt)
                        continue
                    fh = fhs.get(sub[1])
                    if fh is not None:
                        self._dispatch_fast(sub[0], sub[1], fh, sub[2])
                    else:
                        spawn(self._dispatch(sub[0], sub[1], sub[2]))
                return
            if dl is not None and time.time() > dl + DEADLINE_SKEW_SLACK_S:
                # Expired before dispatch (gray link delivered it late):
                # refuse with the typed first-line error contract instead
                # of burning handler work whose reply nobody waits for.
                if mid != 0:
                    self._maybe_reply(
                        mid, a, 1,
                        f"DeadlineExceededError: deadline exceeded "
                        f"before dispatch of {a}")
                return
            rt = self._router
            if rt is not None:
                # Sharded daemon connection: shard-local handlers run
                # right here on the shard's loop; everything else joins
                # the batched hop to the daemon's main loop (one
                # call_soon_threadsafe per ready-wave, arrival order
                # preserved).
                lh = rt.local.get(a)
                if lh is None:
                    rt.submit(self, mid, a, b, deadline=dl)
                else:
                    self._dispatch_fast(mid, a, lh, b, deadline=dl,
                                        fallback=rt)
                return
            fh = self.fast_handlers.get(a)
            if fh is not None:
                self._dispatch_fast(mid, a, fh, b, deadline=dl)
            else:
                spawn(self._dispatch(mid, a, b, deadline=dl))
        else:  # response [mid, status, payload]
            fut = self._pending.pop(mid, None)
            if fut is not None and not fut.done():
                if a == 0:
                    fut.set_result(b)
                else:
                    fut.set_exception(RemoteError(b))

    def _teardown(self):
        if self._closed:
            return
        self._closed = True
        self._retire_io_stats()
        self._native_rx_end(resume=False)
        if self._dup_fd >= 0:
            try:
                os.close(self._dup_fd)
            except OSError:
                pass
            self._dup_fd = -1
        if self._framer is not None:
            try:
                self._framer.close()
            except Exception:
                pass
            self._framer = None
            self._use_native = False
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        for taker in self._raw_takers.values():
            if not taker[1].done():
                taker[1].set_exception(
                    ConnectionLost(f"connection {self.name} lost"))
        self._raw_takers.clear()
        self._raw_sinks.clear()
        self._raw_orphans.clear()
        self._raw_cur = None
        self._resume_writing()  # unblock drain waiters
        try:
            if self.transport is not None:
                self.transport.close()
        except Exception:
            pass
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    def _retire_io_stats(self) -> None:
        """Fold final I/O counters into the process-wide retired totals
        (io_stats_snapshot) before the connection object goes away.
        Idempotent: also reached by the bridged-close path when the
        owning loop died before a real teardown could run — the
        counters' export contract is monotonic, so a live-then-GC'd
        connection must never make them go backward."""
        if getattr(self, "_stats_retired", False):
            return
        self._stats_retired = True
        with _IO_LOCK:
            for k, v in self.io_stats.items():
                _IO_RETIRED[k] = _IO_RETIRED.get(k, 0) + v
            _IO_RETIRED["connections"] = \
                _IO_RETIRED.get("connections", 0) + 1
            _LIVE_CONNS.discard(self)

    def _dispatch_fast(self, mid: int, method: str, fh, payload,
                       deadline: Optional[float] = None,
                       fallback: Optional["ShardRouter"] = None):
        """Inline dispatch for fast handlers (see __init__): no Task per
        request.  Chaos injection and error replies match _dispatch.
        `fallback` routes a FAST_FALLBACK result through the shard
        router's main-loop hop instead of a local coroutine dispatch
        (shard-local handlers bail out to the daemon's main loop for
        their slow/state-mutating branches)."""
        if _chaos and _chaos.should_fail(method, "req"):
            return  # drop silently; caller times out / retries
        tok = _handler_deadline.set(deadline) if deadline is not None \
            else None
        try:
            res = fh(self, payload)
        except Exception as e:
            if mid != 0:
                import traceback
                self._maybe_reply(mid, method, 1,
                                  f"{type(e).__name__}: {e}\n"
                                  f"{traceback.format_exc()}")
            return
        finally:
            if tok is not None:
                # The recv path shares one context across callbacks: an
                # unreset deadline would leak into unrelated dispatches.
                _handler_deadline.reset(tok)
        if res is FAST_FALLBACK:
            # The request-side chaos check already ran above — skip it in
            # _dispatch or fallback requests would see a doubled drop rate.
            if fallback is not None:
                fallback.submit(self, mid, method, payload,
                                deadline=deadline, skip_req_chaos=True)
            else:
                spawn(self._dispatch(mid, method, payload,
                                     skip_req_chaos=True,
                                     deadline=deadline))
            return
        if isinstance(res, RawPayload) and mid == 0:
            res.close()
            return
        if isinstance(res, asyncio.Future):
            if mid == 0:
                return  # one-way: nothing awaits the outcome
            def _cb(fut):
                try:
                    body, status = fut.result(), 0
                except Exception as e:  # noqa: BLE001
                    import traceback
                    status = 1
                    body = (f"{type(e).__name__}: {e}\n"
                            + "".join(traceback.format_exception(e)))
                self._maybe_reply(mid, method, status, body)
            res.add_done_callback(_cb)
            return
        if mid != 0:
            self._maybe_reply(mid, method, 0, res)

    def _maybe_reply(self, mid: int, method: str, status: int, body):
        if self._loop is not None and not self._on_owner_loop():
            # Hop-dispatched handler completing on the daemon's main
            # loop: hand the reply to the owning shard loop.  A
            # RawPayload's release callback touches state owned by THIS
            # loop (store pins, arena refcounts) — wrap it to run back
            # here once the shard is done with the bytes.
            if isinstance(body, RawPayload) and body.release is not None:
                try:
                    here = asyncio.get_running_loop()
                except RuntimeError:
                    here = None
                if here is not None:
                    rel = body.release
                    body.release = \
                        lambda: here.call_soon_threadsafe(rel)
            try:
                self._loop.call_soon_threadsafe(
                    self._maybe_reply, mid, method, status, body)
            except RuntimeError:        # owner loop already closed
                if isinstance(body, RawPayload):
                    body.close()
            return
        if isinstance(body, RawPayload):
            if (_chaos and _chaos.should_fail(method, "resp")) \
                    or self._closed or mid == 0:
                body.close()        # dropped: release pins, send nothing
                return
            self.send_raw(mid, body)
            return
        if _chaos and _chaos.should_fail(method, "resp"):
            return
        if not self._closed:
            self._send_reply(mid, status, body)

    def _send_reply(self, mid: int, status: int, body) -> None:
        """Queue one response; all replies of the current loop tick leave
        as a single __batch_resp__ frame (see _resp_buf)."""
        self._resp_buf.append([mid, status, body])
        if not self._resp_scheduled:
            self._resp_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_resp)

    def _flush_resp(self) -> None:
        self._resp_scheduled = False
        buf = self._resp_buf
        if self._closed or not buf:
            buf.clear()
            return
        self._resp_buf = []
        if len(buf) == 1:
            self._send_frame(buf[0])
        else:
            self._send_frame([0, "__batch_resp__", buf])

    async def _dispatch(self, mid: int, method: str, payload,
                        skip_req_chaos: bool = False,
                        deadline: Optional[float] = None):
        handler = self.handlers.get(method)
        if (not skip_req_chaos and _chaos
                and _chaos.should_fail(method, "req")):
            return  # drop silently; caller times out / retries
        if deadline is not None:
            # This dispatch runs in its own Task (own context copy): the
            # deadline is visible to the whole handler coroutine and
            # everything it awaits, and dies with the Task — no reset
            # bookkeeping needed.
            _handler_deadline.set(deadline)
        try:
            if handler is None:
                raise RpcError(f"no handler for {method!r}")
            result = handler(self, payload)
            if isinstance(result, Awaitable):
                result = await result
            status, body = 0, result
        except Exception as e:
            import traceback
            # First line is the machine-readable "TypeName: message"
            # contract — callers classify remote errors by it (e.g.
            # core_worker's ObjectTransferError handling); the traceback
            # follows for humans only.
            status, body = 1, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
        if mid == 0:
            if isinstance(body, RawPayload):
                body.close()    # one-way caller can't receive it: drop pins
            return  # one-way
        self._maybe_reply(mid, method, status, body)

    async def drain(self):
        """Wait until the transport's write buffer falls below the high
        watermark (cheap no-op when unpaused — matches StreamWriter.drain).
        Not cross-thread bridged: the waiter future must live on the
        owning loop (resume_writing resolves it there)."""
        if self._loop is not None and not self._on_owner_loop():
            raise RpcError("drain() is not cross-thread safe")
        if self._paused and not self._closed:
            w = asyncio.get_running_loop().create_future()
            self._drain_waiters.append(w)
            await w

    @staticmethod
    def _effective_timeout(timeout: float | None,
                           deadline: float | None) -> float | None:
        """Resolve a call's wait bound: explicit timeout wins; None picks
        up the process default (set_default_call_timeout); 0 opts out
        entirely.  A deadline further caps whatever that produced, and an
        already-expired deadline raises immediately."""
        eff = _default_call_timeout if timeout is None else (timeout or None)
        if deadline is not None:
            remaining = deadline - time.time()
            # Slack: the deadline may carry a remote clock's stamp (an
            # inherited budget).  Within the skew window the call still
            # goes out on a short floor and resolves typed via the
            # TimeoutError -> DeadlineExceededError conversion.
            if remaining <= -DEADLINE_SKEW_SLACK_S:
                raise deadline_exceeded(
                    "deadline already exceeded before call was issued")
            remaining = max(remaining, 0.1)
            eff = remaining if eff is None else min(eff, remaining)
        return eff

    async def call(self, method: str, payload=None,
                   timeout: float | None = None,
                   deadline: float | None = None):
        if self._loop is not None and not self._on_owner_loop():
            return await self._bridge(
                self.call(method, payload, timeout, deadline))
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        eff_timeout = self._effective_timeout(timeout, deadline)
        mid = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        self._send_frame([mid, method, payload] if deadline is None
                         else [mid, method, payload, deadline])
        if self._closed:
            if fut.done():
                fut.exception()  # consume, avoid never-retrieved warning
            raise ConnectionLost(f"connection {self.name} lost on send")
        await self.drain()
        try:
            if eff_timeout:
                return await asyncio.wait_for(fut, eff_timeout)
            return await fut
        except asyncio.TimeoutError:
            if deadline is not None and time.time() >= deadline:
                raise deadline_exceeded(
                    f"{method} deadline exceeded after "
                    f"{eff_timeout:.3f}s") from None
            raise
        finally:
            if fut.cancelled():
                self._pending.pop(mid, None)    # reap timed-out entries

    async def call_raw(self, method: str, payload, sink,
                       timeout: float | None = None,
                       deadline: float | None = None):
        """Call whose successful response arrives as a raw out-of-band
        payload scattered into `sink` (a writable buffer — filled from
        offset 0 — or a callable receiving sequential memoryview pieces).
        Resolves to the byte count scattered.  A peer replying with a
        normal msgpack frame instead (absence marker, typed error, or a
        legacy bytes body) resolves to that value — callers handle both."""
        if self._loop is not None and not self._on_owner_loop():
            return await self._bridge(
                self.call_raw(method, payload, sink, timeout, deadline))
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        eff_timeout = self._effective_timeout(timeout, deadline)
        mid = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        self._raw_sinks[mid] = sink
        try:
            self._send_frame([mid, method, payload] if deadline is None
                             else [mid, method, payload, deadline])
            if self._closed:
                if fut.done():
                    fut.exception()  # consume
                raise ConnectionLost(f"connection {self.name} lost on send")
            await self.drain()
            try:
                if eff_timeout:
                    return await asyncio.wait_for(fut, eff_timeout)
                return await fut
            except asyncio.TimeoutError:
                if deadline is not None and time.time() >= deadline:
                    raise deadline_exceeded(
                        f"{method} deadline exceeded") from None
                raise
        finally:
            self._raw_sinks.pop(mid, None)
            # The caller is done with this sink (success, timeout or
            # cancellation).  If its payload is still streaming in, defuse
            # the reception: the destination buffer may be released,
            # aborted, or reallocated the moment we return — late bytes
            # must be discarded, not scattered into freed memory.
            cur = self._raw_cur
            if cur is not None and cur[0] == mid:
                cur[2] = None
            if fut.cancelled():
                # Timed-out/cancelled calls must not accumulate in
                # _pending on long-lived peer connections (per-chunk
                # timeouts are a designed recurrent event); a late reply
                # for the reaped mid is ignored / orphan-buffered.
                self._pending.pop(mid, None)

    async def call_with_raw(self, method: str, payload: dict,
                            body: RawPayload,
                            timeout: float | None = None):
        """Call whose REQUEST carries a raw payload: a normal request
        frame (its payload dict gains 'raw_id' and 'nbytes' so the
        handler can `await conn.take_raw(raw_id)`), immediately followed
        by the raw frame.  Returns the response; timed-out entries are
        reaped from _pending like call()/call_raw()."""
        if self._loop is not None and not self._on_owner_loop():
            return await self._bridge(
                self.call_with_raw(method, payload, body, timeout))
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        mid = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        payload = dict(payload)
        # Negative rid: request-side payloads live in their own id space so
        # they can never collide with a pending outbound call of the
        # RECEIVER (whose mids are positive and independently allocated).
        payload["raw_id"] = -mid
        payload["nbytes"] = body.nbytes
        # Bulk-data call: the control_call_timeout_s default deliberately
        # does NOT apply — a multi-GB upload legitimately outlives any
        # unary-call bound.  Callers pass an explicit timeout if they
        # want one.
        eff_timeout = timeout or None
        self._send_frame([mid, method, payload])
        self.send_raw(-mid, body)
        # Backpressure like call()/call_raw(): bound userspace buffering
        # at the transport's high watermark for multi-GB uploads.
        await self.drain()
        try:
            if eff_timeout:
                return await asyncio.wait_for(fut, eff_timeout)
            return await fut
        finally:
            if fut.cancelled():
                self._pending.pop(mid, None)

    def notify(self, method: str, payload=None):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        if self._loop is not None and not self._on_owner_loop():
            # Fire-and-forget from a foreign thread (e.g. a daemon main
            # loop publishing to a shard-owned subscriber): hand the
            # frame to the owning loop.  A close racing the hop drops
            # the notify exactly like a close racing a same-loop send.
            try:
                self._loop.call_soon_threadsafe(
                    self._notify_local, method, payload)
            except RuntimeError:
                raise ConnectionLost(
                    f"connection {self.name} loop closed") from None
            return
        self._send_frame([0, method, payload])

    def _notify_local(self, method: str, payload) -> None:
        if not self._closed:
            self._send_frame([0, method, payload])

    def call_many(self, method: str, payloads) -> list:
        """Issue many independent calls in ONE frame; returns their futures.

        Semantically identical to [call(method, p) for p in payloads] —
        each sub-call dispatches and replies independently on the peer, so
        one slow/failed call never gates another — but the request framing
        is amortized: ~18us/op vs ~80us/op for pipelined call()s between
        single-core processes. Callers await the returned futures
        individually (per-call errors arrive as RemoteError on that future
        only). Connection loss fails all returned futures."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        if self._loop is not None and not self._on_owner_loop():
            # Deliberately NOT bridged (the returned futures would
            # belong to the wrong loop): fail loudly instead of
            # corrupting framing state from a foreign thread.
            raise RpcError("call_many is not cross-thread safe; "
                           "issue individual call()s instead")
        loop = asyncio.get_running_loop()
        futs, batch = [], []
        for p in payloads:
            mid = self._next_id
            self._next_id += 1
            fut = loop.create_future()
            self._pending[mid] = fut
            futs.append(fut)
            batch.append([mid, method, p])
        self._send_frame([0, "__batch__", batch])
        return futs

    _BIG_FRAME = 256 * 1024

    def _send_frame(self, obj) -> None:
        data = _pack(obj)
        if len(data) >= self._BIG_FRAME:
            # Large payloads skip the coalescing join entirely: flush any
            # queued small frames, then hand the big buffer straight to
            # the transport (no extra copy).
            self._flush_wbuf()
            if self._closed:
                return
            self._tx(data)
            return
        self._wbuf.append(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_wbuf)

    def _flush_wbuf(self) -> None:
        self._flush_scheduled = False
        if self._closed or not self._wbuf:
            self._wbuf.clear()
            return
        buf, self._wbuf = self._wbuf, []
        self.io_stats["tx_frames"] += len(buf)
        if len(buf) > 1 and self._vectored_ok():
            # Native framer: the whole wave leaves in one writev — no
            # join allocation, no per-frame syscalls.
            self._tx_vectored(buf)
            return
        # Always one write: on a drained transport each write() is an
        # immediate socket send, so per-frame writes cost a syscall each.
        # _tx is a direct transport.write unless link chaos is enabled.
        self._tx(buf[0] if len(buf) == 1 else b"".join(buf))

    def _vectored_ok(self) -> bool:
        """Direct gather-writes are only safe when nothing is queued
        ahead of us: no chaos plan (its delayed queue must see every
        byte), no transport write buffer (ordering), no pause
        (backpressure already in force)."""
        return (self._use_native and _link_chaos is None
                and not self._paused and self.transport is not None
                and self.transport.get_write_buffer_size() == 0)

    def _tx_vectored(self, buffers) -> bool:
        """writev the buffers; an EAGAIN-unsent tail is handed to the
        transport so its high-watermark machinery (pause_writing ->
        drain()) keeps governing memory — a vectored wave must respect
        backpressure, not buffer itself wholesale.  Returns True when
        every byte reached the kernel (the caller may then release
        pinned views immediately)."""
        from . import rpcframe
        try:
            w, total, err, nsys = rpcframe.writev(self._sock_fd, buffers)
        except Exception:
            logger.warning("vectored write failed on %s; falling back",
                           self.name, exc_info=True)
            self._tx(b"".join(bytes(b) for b in buffers))
            return False
        st = self.io_stats
        st["tx_writev"] += 1
        st["tx_syscalls"] += max(nsys, 1)
        st["tx_bytes"] += w
        if err:
            self._teardown()
            return False
        if w == total:
            return True
        # Partial: queue the tail on the transport (counts as one more
        # submission; the transport sends it as the socket drains).
        st["tx_syscalls"] += 1
        skip = w
        try:
            for b in buffers:
                nb = _nbytes(b)
                if skip >= nb:
                    skip -= nb
                    continue
                mv = memoryview(b)
                self.transport.write(mv[skip:] if skip else mv)
                st["tx_bytes"] += nb - skip
                skip = 0
        except (ConnectionError, OSError):
            self._teardown()
        return False

    async def close(self):
        if self._loop is not None and not self._on_owner_loop() \
                and not self._closed:
            try:
                await self._bridge(self.close())
            except (ConnectionLost, RuntimeError):
                # Owner loop gone: no transport work is possible, but
                # the accounting contract still holds.
                self._closed = True
                self._retire_io_stats()
            return
        # Push out coalesced frames before tearing down — a notify()
        # immediately followed by close() (e.g. the GCS's kill delivery)
        # must still reach the peer.
        self._flush_resp()
        self._flush_wbuf()
        if not self._closed and self.transport is not None:
            try:
                self.transport.write_eof()
            except (OSError, RuntimeError, NotImplementedError):
                pass
        self._teardown()


# ---------------------------------------------------------------------------
# Daemon I/O shards (see module docstring).
# ---------------------------------------------------------------------------
class IoShard:
    """One shard: a dedicated thread running its own event loop."""

    __slots__ = ("index", "label", "loop", "thread")

    def __init__(self, index: int, label: str, loop, thread):
        self.index = index
        self.label = label
        self.loop = loop
        self.thread = thread


class IoShardPool:
    """N event-loop threads owning a daemon's accepted connections.

    Each shard thread runs a plain asyncio loop (eager tasks enabled,
    busy-fraction probe installed under the label `shard<i>`); the
    accepting server distributes connections round-robin with
    `pick()`.  `close()` stops the loops and joins the threads —
    always AFTER the server has closed its connections, or bridged
    closes would hang."""

    def __init__(self, n: int, name: str = "daemon",
                 busy_probes: bool = True):
        self.shards: list = []
        self._rr = 0
        for i in range(n):
            loop = asyncio.new_event_loop()
            label = f"shard{i}"
            ready = threading.Event()

            def _run(loop=loop, label=label, ready=ready):
                asyncio.set_event_loop(loop)
                enable_eager_tasks(loop)
                if busy_probes:
                    try:
                        from . import loopmon
                        loopmon.install(label, loop)
                    except Exception:   # probes are never load-bearing
                        pass
                loop.call_soon(ready.set)
                try:
                    loop.run_forever()
                finally:
                    try:
                        loop.close()
                    except Exception:
                        pass

            t = threading.Thread(target=_run, daemon=True,
                                 name=f"{name}-io-{label}")
            t.start()
            # Wait for the loop to actually run before putting the
            # shard in the accept rotation: a connection adopted by a
            # never-started loop would hang its clients silently.
            if ready.wait(timeout=5.0):
                self.shards.append(IoShard(i, label, loop, t))
            else:
                logger.warning("I/O shard %s/%s failed to start; "
                               "excluding it from the rotation",
                               name, label)

    def __len__(self) -> int:
        return len(self.shards)

    def pick(self) -> IoShard:
        sh = self.shards[self._rr % len(self.shards)]
        self._rr += 1
        return sh

    def close(self, timeout: float = 2.0) -> None:
        for sh in self.shards:
            try:
                sh.loop.call_soon_threadsafe(sh.loop.stop)
            except RuntimeError:
                pass
        for sh in self.shards:
            sh.thread.join(timeout)
        self.shards = []


class ShardRouter:
    """Per-shard seam into the daemon's main loop.

    Shard threads own the wire; the daemon's shared state stays
    single-threaded on the main loop.  `local` maps method name ->
    SYNC shard-local handler (fast-handler contract: returns a result,
    a Future, or FAST_FALLBACK to punt this request to the main loop).
    Every other request is `submit()`ed: a ready-wave of submissions
    (all frames processed in the current shard-loop iteration, across
    all of this shard's connections) crosses to the main loop in ONE
    call_soon_threadsafe, and `_run_batch` dispatches them there in
    arrival order through the normal `Connection._dispatch` path —
    identical semantics (chaos, deadlines, error contract), different
    thread.  Replies hop back through `Connection._maybe_reply`'s
    cross-thread seam."""

    __slots__ = ("shard_loop", "main_loop", "local", "_buf",
                 "_scheduled", "hops", "submitted")

    def __init__(self, shard_loop, main_loop,
                 local: Dict[str, Callable] | None = None):
        self.shard_loop = shard_loop
        self.main_loop = main_loop
        self.local = local or {}
        self._buf: list = []
        self._scheduled = False
        # Observability (asserted by tests, exported by the daemons):
        # hops counts main-loop crossings, submitted counts requests —
        # submitted/hops is the wave-batching factor.
        self.hops = 0
        self.submitted = 0

    def submit(self, conn: "Connection", mid: int, method: str, payload,
               deadline: Optional[float] = None,
               skip_req_chaos: bool = False) -> None:
        # Shard-loop only; no lock needed (the flush is scheduled on
        # the same loop, so buffer and flag are single-threaded).
        self.submitted += 1
        self._buf.append((conn, mid, method, payload, deadline,
                          skip_req_chaos))
        if not self._scheduled:
            self._scheduled = True
            self.shard_loop.call_soon(self._flush)

    def _flush(self) -> None:
        self._scheduled = False
        buf, self._buf = self._buf, []
        if not buf:
            return
        self.hops += 1
        try:
            self.main_loop.call_soon_threadsafe(self._run_batch, buf)
        except RuntimeError:
            # Main loop gone (daemon teardown): the peers' calls fail
            # via connection close, same as a daemon crash.
            pass

    def _run_batch(self, buf: list) -> None:
        # MAIN loop: dispatch in arrival order.  With eager tasks the
        # common non-suspending handler completes (and queues its
        # reply hop) inline here.
        for conn, mid, method, payload, deadline, skip in buf:
            if conn._closed:
                continue
            spawn(conn._dispatch(mid, method, payload,
                                 skip_req_chaos=skip, deadline=deadline))


def make_io_shard_pool(name: str) -> Optional[IoShardPool]:
    """Config-driven pool for a daemon: None when `daemon_io_shards`
    resolves to 0 (single-loop mode)."""
    try:
        from .config import resolve_io_shards
        n = resolve_io_shards()
    except Exception:
        return None
    return IoShardPool(n, name=name) if n > 0 else None


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------
class RpcServer:
    def __init__(self, handlers: Dict[str, Callable], name: str = "server",
                 on_client_close: Callable | None = None,
                 fast_handlers: Dict[str, Callable] | None = None,
                 auth_token=DEFAULT_TOKEN, native: bool | None = None,
                 io_shards: "IoShardPool | None" = None,
                 shard_handlers: Dict[str, Callable] | None = None):
        self.handlers = handlers
        self.fast_handlers = fast_handlers
        self.name = name
        self.native = native
        self.auth_token = _resolve_token(auth_token)
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[Connection] = set()
        # Called with the Connection when a client disconnects — lets the
        # agent reclaim leases whose owner died (reference: raylet
        # returning leases on client disconnect).
        self.on_client_close = on_client_close
        # Daemon I/O sharding: with a pool, accepted connections are
        # adopted by shard loops; `shard_handlers` (method -> SYNC
        # callable, FAST_FALLBACK allowed) run shard-local, everything
        # else hops to this server's home loop.  connections/close
        # callbacks always run on the home loop regardless of mode.
        self.io_shards = io_shards if io_shards and len(io_shards) else None
        self.shard_handlers = shard_handlers or {}
        self._routers: Dict[int, ShardRouter] = {}
        self._home_loop = None
        self._lsock = None
        self._accept_task: Optional[asyncio.Task] = None

    def _conn_closed(self, c: "Connection") -> None:
        self.connections.discard(c)
        if self.on_client_close is not None:
            try:
                self.on_client_close(c)
            except Exception:
                logger.exception("on_client_close failed")

    def _factory(self) -> _WireProtocol:
        conn = Connection(self.handlers, name=self.name,
                          on_close=self._conn_closed,
                          fast_handlers=self.fast_handlers,
                          auth_token=self.auth_token,
                          on_connect=self.connections.add,
                          native=self.native)
        return _WireProtocol(conn)

    def _router_for(self, shard: IoShard) -> ShardRouter:
        rt = self._routers.get(shard.index)
        if rt is None:
            rt = self._routers[shard.index] = ShardRouter(
                shard.loop, self._home_loop, self.shard_handlers)
        return rt

    def shard_stats(self) -> Dict[str, int]:
        """Hop/batching counters for the unified export and tests."""
        return {
            "shards": len(self.io_shards) if self.io_shards else 0,
            "hops": sum(rt.hops for rt in self._routers.values()),
            "submitted": sum(rt.submitted
                             for rt in self._routers.values()),
        }

    async def _accept_loop(self, sock) -> None:
        """Sharded accept: the home loop accepts, a shard loop adopts.
        connect_accepted_socket builds the transport ON the shard loop,
        so every byte of this connection's wire work lands there."""
        loop = asyncio.get_running_loop()
        home = loop
        while True:
            try:
                client, _addr = await loop.sock_accept(sock)
            except asyncio.CancelledError:
                raise
            except OSError:
                if self._lsock is None:
                    return              # closed under us: normal exit
                await asyncio.sleep(0.05)
                continue
            shard = self.io_shards.pick()
            rt = self._router_for(shard)
            conn = Connection(
                self.handlers, name=self.name,
                # Lifecycle callbacks touch daemon state: always home.
                on_close=lambda c: home.call_soon_threadsafe(
                    self._conn_closed, c),
                fast_handlers=self.fast_handlers,
                auth_token=self.auth_token,
                on_connect=lambda c: home.call_soon_threadsafe(
                    self.connections.add, c),
                native=self.native)
            conn._router = rt
            try:
                cf = asyncio.run_coroutine_threadsafe(
                    shard.loop.connect_accepted_socket(
                        lambda c=conn: _WireProtocol(c), client),
                    shard.loop)
            except RuntimeError:        # shard loop gone (teardown race)
                client.close()
                continue

            def _done(f, sock_=client):
                try:
                    f.result()
                except Exception:
                    logger.warning("shard adoption failed on %s",
                                   self.name, exc_info=True)
                    try:
                        sock_.close()
                    except OSError:
                        pass

            cf.add_done_callback(_done)

    async def start_tcp(self, host: str = "127.0.0.1", port: int = 0):
        loop = asyncio.get_running_loop()
        if self.io_shards is not None:
            import socket
            sock = socket.create_server((host, port), backlog=1024)
            sock.setblocking(False)
            self._lsock = sock
            self._home_loop = loop
            self._accept_task = spawn(self._accept_loop(sock))
            return sock.getsockname()[:2]
        self._server = await loop.create_server(self._factory, host, port)
        return self._server.sockets[0].getsockname()[:2]

    async def start_unix(self, path: str):
        loop = asyncio.get_running_loop()
        self._server = await loop.create_unix_server(self._factory, path)
        return path

    async def close(self):
        if self._accept_task is not None:
            task, self._accept_task = self._accept_task, None
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._lsock is not None:
            sock, self._lsock = self._lsock, None
            try:
                sock.close()
            except OSError:
                pass
        if self._server:
            self._server.close()
        for c in list(self.connections):
            try:
                # Bounded: a bridged close must not hang server teardown
                # if a shard loop is already wedged/stopped.
                await asyncio.wait_for(c.close(), 2)
            except (asyncio.TimeoutError, RuntimeError):
                pass
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass


class ReconnectingConnection:
    """Connection wrapper that transparently re-dials after loss — the
    client half of GCS fault tolerance (reference: RetryableGrpcClient +
    RayletNotifyGCSRestart, core_worker.proto:467).  A lost call is
    retried ONCE after reconnect; GCS mutations are id-keyed upserts, so
    the replay is idempotent.  `on_reconnect(conn)` runs after every
    successful (re)dial — registration/subscription goes there.

    `resolver` (optional, sync, returns an address or None) is consulted
    before EVERY dial attempt, not just the first: after a GCS failover
    the advertised address points at the promoted standby, and a client
    pinned to the address it cached at init() would redial a corpse
    forever.  Re-homing rides the same jittered backoff as a plain
    restart — no separate failover code path on the client."""

    def __init__(self, address, handlers: Dict[str, Callable] | None = None,
                 name: str = "client",
                 on_reconnect: Callable | None = None,
                 dial_retries: int = 75, retry_delay: float = 0.2,
                 auth_token=DEFAULT_TOKEN,
                 resolver: Callable | None = None):
        self.address = address
        self.handlers = handlers
        self.name = name
        self.on_reconnect = on_reconnect
        self.dial_retries = dial_retries
        self.retry_delay = retry_delay
        self.auth_token = auth_token
        self.resolver = resolver
        self._conn: Connection | None = None
        self._lock = asyncio.Lock()
        self._closed = False

    @property
    def closed(self):
        return self._closed

    async def ensure(self) -> Connection:
        """Eagerly dial (and run on_reconnect) — the supported way to
        establish the first connection at startup."""
        return await self._ensure()

    async def _ensure(self) -> Connection:
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        if self._conn is not None and not self._conn.closed:
            return self._conn
        async with self._lock:
            if self._conn is not None and not self._conn.closed:
                return self._conn
            self._conn = await self._dial()
            if self.on_reconnect is not None:
                res = self.on_reconnect(self._conn)
                if isinstance(res, Awaitable):
                    await res
            return self._conn

    async def _dial(self) -> Connection:
        # One single-shot connect per retry round so the resolver runs
        # between rounds — connect()'s own retry loop would pin the
        # whole budget to the address resolved once up front.
        last_err: Exception | None = None
        for attempt in range(self.dial_retries):
            if self.resolver is not None:
                try:
                    addr = self.resolver()
                except Exception:
                    addr = None  # transient resolve failure: last known
                if addr:
                    self.address = (addr if isinstance(addr, str)
                                    else (addr[0], addr[1]))
            try:
                return await connect(
                    self.address, self.handlers, retries=1, retry_delay=0,
                    name=self.name, auth_token=self.auth_token)
            except ConnectionLost as e:
                last_err = e
                if attempt + 1 >= self.dial_retries:
                    break
                await asyncio.sleep(
                    _backoff_delay(attempt, self.retry_delay))
        raise ConnectionLost(
            f"cannot connect to {self.address} ({self.name}): {last_err}")

    async def call(self, method: str, payload=None,
                   timeout: float | None = None,
                   deadline: float | None = None):
        for attempt in range(2):
            conn = await self._ensure()
            try:
                return await conn.call(method, payload, timeout,
                                       deadline=deadline)
            except ConnectionLost:
                if attempt:
                    raise
        raise ConnectionLost(f"connection {self.name} lost")

    def notify(self, method: str, payload=None):
        if self._conn is None or self._conn.closed:
            raise ConnectionLost(f"connection {self.name} not established")
        self._conn.notify(method, payload)

    async def close(self):
        self._closed = True
        if self._conn is not None:
            await self._conn.close()


# ---------------------------------------------------------------------------
# Client-side connect with retry
# ---------------------------------------------------------------------------
async def connect(address, handlers: Dict[str, Callable] | None = None,
                  retries: int = 10, retry_delay: float = 0.2,
                  name: str = "client", on_close: Callable | None = None,
                  auth_token=DEFAULT_TOKEN,
                  native: bool | None = None) -> Connection:
    """address: (host, port) tuple or unix socket path str."""
    loop = asyncio.get_running_loop()
    send_token = _resolve_token(auth_token)
    last_err: Exception | None = None
    for attempt in range(retries):
        conn = Connection(handlers, name=name, on_close=on_close,
                          send_token=send_token, native=native)
        try:
            if isinstance(address, str):
                await loop.create_unix_connection(
                    lambda: _WireProtocol(conn), address)
            else:
                await loop.create_connection(
                    lambda: _WireProtocol(conn), address[0], address[1])
            return conn
        except (ConnectionError, OSError, FileNotFoundError) as e:
            last_err = e
            # Jittered backoff (see _backoff_delay): after a GCS restart
            # or netsplit heal, every client of a node redials at once —
            # identical deterministic delays would synchronize the whole
            # fleet into a thundering herd on each retry round.
            await asyncio.sleep(_backoff_delay(attempt, retry_delay))
    raise ConnectionLost(f"cannot connect to {address}: {last_err}")
