"""Distributed reference counting: ownership, borrowing, lineage.

Equivalent of the reference's ReferenceCounter (reference:
src/ray/core_worker/reference_count.cc, 1,831 LoC). An object is freed when
ALL of these drop to zero (reference: "owner frees when local refs +
submitted refs + borrower set are all empty"):

- `local`      live Python ObjectRefs in this process
- `submitted`  in-flight tasks carrying the ref as an argument
- `escaped`    serialized copies nested inside other live values — pinned by
               containment: put()/task-return values that pickled this ref
               hold one escape pin each, released when the container is freed
- `borrowers`  remote workers holding live deserialized copies, registered
               via borrow_add / borrow_release RPCs from the borrower's own
               reference counter

Borrower-side entries carry the owner's address so GC of the last local ref
sends borrow_release to the owner. A borrower that dies without releasing
leaks its pin (the reference GCs these through worker-failure pubsub; here
lineage reconstruction backstops a premature free instead).

`lineage` retains the creating task's spec for owned task returns so lost
primaries can be re-executed (reference: task_manager.h:227 ResubmitTask).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple


class _Ref:
    __slots__ = ("local", "submitted", "escaped", "borrowers", "lineage",
                 "owner_addr", "registered", "borrow_epoch")

    def __init__(self):
        self.local = 0
        self.submitted = 0
        self.escaped = 0
        self.borrowers: Set[bytes] = set()
        self.lineage = None
        self.owner_addr = None    # None = owned by this process
        self.registered = False   # borrower side: borrow_add sent to owner
        self.borrow_epoch = 0     # borrower side: per-registration generation

    def freeable(self) -> bool:
        return (self.local <= 0 and self.submitted <= 0
                and self.escaped <= 0 and not self.borrowers)


class ReferenceCounter:
    def __init__(self, on_zero: Callable[[bytes, Optional[tuple]], None],
                 on_borrow: Callable[[bytes, tuple], None] | None = None):
        self._refs: Dict[bytes, _Ref] = {}
        # Count of refs with registered=True: borrowed_from() is called on
        # EVERY task reply (it piggybacks retained borrows) and would scan
        # the whole ref table each time; the common case — this process
        # borrows nothing — must be O(1).
        self._registered_n = 0
        # (oid, worker_id) -> (release time, max released epoch);
        # insertion-ordered for pruning.
        self._release_tombstones: Dict[Tuple[bytes, bytes],
                                       Tuple[float, int]] = {}
        self._borrow_epoch = 0
        self._contained: Dict[bytes, List[Tuple[bytes, Optional[tuple]]]] = {}
        self._lock = threading.Lock()
        self._on_zero = on_zero
        self._on_borrow = on_borrow  # called once per newly-borrowed oid

    # ------------------------------------------------------------- owned ----
    def add_owned(self, object_id: bytes, lineage=None):
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            if lineage is not None:
                ref.lineage = lineage

    def add_local_ref(self, object_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).local += 1

    def remove_local_ref(self, object_id: bytes):
        self._dec(object_id, "local")

    def add_submitted(self, object_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).submitted += 1

    def remove_submitted(self, object_id: bytes):
        self._dec(object_id, "submitted")

    def add_escape_pin(self, object_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).escaped += 1

    def release_escape_pin(self, object_id: bytes):
        self._dec(object_id, "escaped")

    def add_borrower(self, object_id: bytes, worker_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).borrowers.add(worker_id)

    def add_borrower_from_reply(self, object_id: bytes, worker_id: bytes,
                                epoch: int = 0):
        """Borrow registration carried in a task reply. Unlike add_borrower
        this (a) never resurrects an already-freed ref record and (b) skips
        registrations whose borrow_release already landed — the reply and
        the release travel different sockets, so a borrow dropped between
        the worker's snapshot and this call could otherwise re-register
        forever. Staleness is decided by the borrower-minted epoch: a
        release with epoch >= the reply's epoch covers it; a NEWER borrow
        (fresh epoch) of the same pair registers normally."""
        with self._lock:
            self._prune_tombstones()
            released = self._release_tombstones.get((object_id, worker_id))
            if released is not None and released[1] >= epoch:
                return
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.borrowers.add(worker_id)

    def remove_borrower(self, object_id: bytes, worker_id: bytes,
                        epoch: int = 0):
        fire = False
        key = (object_id, worker_id)
        with self._lock:
            self._prune_tombstones()
            # Delete-then-insert so a refreshed tombstone moves to the dict
            # tail — pruning walks insertion order from the head.
            old = self._release_tombstones.pop(key, None)
            self._release_tombstones[key] = (
                time.monotonic(), max(epoch, old[1] if old else 0))
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.borrowers.discard(worker_id)
            if ref.freeable():
                del self._refs[object_id]
                if ref.registered:
                    self._registered_n -= 1
                fire = True
        if fire:
            self._fire(object_id, ref)

    def _prune_tombstones(self, ttl: float = 300.0):
        # Called under self._lock; insertion order == refresh order.
        cutoff = time.monotonic() - ttl
        while self._release_tombstones:
            key, (ts, _) = next(iter(self._release_tombstones.items()))
            if ts >= cutoff:
                break
            del self._release_tombstones[key]

    # ---------------------------------------------------------- borrowed ----
    def mark_borrowed(self, object_id: bytes,
                      owner_addr: tuple) -> Optional[int]:
        """Record that this process borrows `object_id` from `owner_addr`.
        Returns the freshly minted borrow epoch the first time (caller sends
        borrow_add carrying it to the owner), None on re-registration."""
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            ref.owner_addr = tuple(owner_addr)
            if not ref.registered:
                ref.registered = True
                self._registered_n += 1
                self._borrow_epoch += 1
                ref.borrow_epoch = self._borrow_epoch
                return ref.borrow_epoch
            return None

    # ------------------------------------------------------- containment ----
    def add_contained(self, container_id: bytes,
                      nested: List[Tuple[bytes, Optional[tuple]]]):
        """Record that `container_id`'s value embeds serialized refs; the
        matching escape pins are taken by the SERIALIZING process (sync for
        its own objects, escape_pin RPC for remote owners) and released via
        pop_contained when the container is freed (reference:
        reference_count.cc AddNestedObjectIds)."""
        if not nested:
            return
        with self._lock:
            self._contained.setdefault(container_id, []).extend(nested)

    def is_tracked(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._refs

    def pop_contained(self, container_id: bytes
                      ) -> List[Tuple[bytes, Optional[tuple]]]:
        with self._lock:
            return self._contained.pop(container_id, [])

    def borrowed_from(self, owner_addr: tuple) -> List[Tuple[bytes, int]]:
        """(object id, borrow epoch) pairs this process currently borrows
        from `owner_addr`. Piggybacked on task replies so the owner learns
        of retained borrows in-band, strictly before it releases the task's
        submitted arg pins (reference: PushTaskReply borrowed-ref
        metadata)."""
        with self._lock:
            if not self._registered_n:
                return []
            owner = tuple(owner_addr)
            return [(oid, r.borrow_epoch) for oid, r in self._refs.items()
                    if r.registered and r.owner_addr == owner]

    # ------------------------------------------------------------ internal --
    def _dec(self, object_id: bytes, field: str):
        fire = False
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            setattr(ref, field, getattr(ref, field) - 1)
            if ref.freeable():
                del self._refs[object_id]
                if ref.registered:
                    self._registered_n -= 1
                fire = True
        if fire:
            self._fire(object_id, ref)

    def _fire(self, object_id: bytes, ref: _Ref):
        try:
            self._on_zero(object_id, ref.owner_addr, ref.borrow_epoch)
        except Exception:
            pass

    # -------------------------------------------------------------- query ---
    def get_lineage(self, object_id: bytes):
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.lineage if ref else None

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def stats(self) -> dict:
        with self._lock:
            return {
                "tracked": len(self._refs),
                "borrowed": sum(1 for r in self._refs.values()
                                if r.owner_addr is not None),
                "with_borrowers": sum(1 for r in self._refs.values()
                                      if r.borrowers),
                "contained": len(self._contained),
            }
