"""Owner-side distributed reference counting.

Equivalent of the reference's ReferenceCounter (reference:
src/ray/core_worker/reference_count.cc, 1,831 LoC). Round-1 scope: exact
local-ref + submitted-task-arg counting for owned objects, with plasma
primary-copy release when the count hits zero (reference: "owner frees when
local refs + submitted refs + borrower set are all empty"). Borrower
registration across workers (the reference's borrowing protocol) is coarse:
refs serialized into task returns or actor state pin the object permanently
until the owner exits. TODO(round 2): full borrow ledger with on-GC release
messages from borrowers.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set


class _Ref:
    __slots__ = ("local", "submitted", "escaped", "lineage")

    def __init__(self):
        self.local = 0        # live Python ObjectRefs in this process
        self.submitted = 0    # in-flight tasks using this as an arg
        self.escaped = False  # serialized out of our control → never auto-free
        self.lineage = None   # task spec that can recreate this object


class ReferenceCounter:
    def __init__(self, on_zero: Callable[[bytes], None]):
        self._refs: Dict[bytes, _Ref] = {}
        self._lock = threading.Lock()
        self._on_zero = on_zero

    def add_owned(self, object_id: bytes, lineage=None):
        with self._lock:
            ref = self._refs.setdefault(object_id, _Ref())
            if lineage is not None:
                ref.lineage = lineage

    def add_local_ref(self, object_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).local += 1

    def remove_local_ref(self, object_id: bytes):
        self._maybe_free(object_id, "local")

    def add_submitted(self, object_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).submitted += 1

    def remove_submitted(self, object_id: bytes):
        self._maybe_free(object_id, "submitted")

    def mark_escaped(self, object_id: bytes):
        with self._lock:
            self._refs.setdefault(object_id, _Ref()).escaped = True

    def _maybe_free(self, object_id: bytes, field: str):
        fire = False
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            if field == "local":
                ref.local -= 1
            else:
                ref.submitted -= 1
            if ref.local <= 0 and ref.submitted <= 0 and not ref.escaped:
                del self._refs[object_id]
                fire = True
        if fire:
            try:
                self._on_zero(object_id)
            except Exception:
                pass

    def get_lineage(self, object_id: bytes):
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.lineage if ref else None

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)
