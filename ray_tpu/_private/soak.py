"""Many-node control-plane soak harness: simulated agent fleets.

Drives one REAL GCS (typically a subprocess via `node.start_gcs`) with
N simulated nodes — each is a minimal RPC server (timestamped `ping`
for the health/clock probes, `drain`/`shutdown` stubs) plus the real
control-plane client traffic an agent generates: one registration, then
a phase-jittered heartbeat loop shipping `report_resources` (with
peer_stats so the GCS's evidence-folding path runs at fleet width),
`task_events` telemetry blobs, and `report_metrics` snapshots every
tick.  No workers, no object store: the point is to load exactly the
per-node control traffic that multiplies at fleet size (ROADMAP item 1
— the O(N) walls live in `_health_loop`, node-view distribution, and
the metrics sink) and to measure it from the outside: registration
latency percentiles, steady-state control RPC latency, heartbeat
rejections, and the GCS's own no-silent-caps drop counters.

Used by tests/test_control_soak.py (100 nodes in tier-1, 500 behind
`-m 'soak and slow'`); docs/control_plane.md documents the how-to.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from typing import Dict, List, Optional

from . import clocks, rpc

__all__ = ["SimulatedNode", "run_soak", "percentile"]


def percentile(values: List[float], p: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[idx]


class SimulatedNode:
    """One fake agent against a real GCS (see module docstring)."""

    def __init__(self, gcs_address: tuple, index: int,
                 period_s: float = 0.25, metrics_rows: int = 8,
                 telemetry_rows: int = 4,
                 session_dir: Optional[str] = None):
        from .ids import NodeID
        self.gcs_address = tuple(gcs_address)
        self.index = index
        # With a session_dir the node runs in HA mode: a reconnecting
        # GCS connection that re-resolves the advertised address on
        # every dial and re-registers after each (re)connect — the same
        # failover re-homing a real agent does.  Used by the GCS
        # failover soak (tests/test_gcs_failover.py).
        self.session_dir = session_dir
        self.last_epoch = 0
        self.reregistrations = 0
        self.node_id = NodeID.from_random().binary()
        self.period_s = period_s
        self.metrics_rows = metrics_rows
        self.telemetry_rows = telemetry_rows
        self.resources = {"CPU": 1.0, "memory": float(1 << 30)}
        # Deterministic per-node phase (mirrors the real agent's
        # pid-jittered heartbeat phase): N nodes spread their ticks
        # across the period instead of stampeding the same instant.
        self._rng = random.Random(0x50AC ^ index)
        self.server: Optional[rpc.RpcServer] = None
        self.address: Optional[tuple] = None
        self.gcs: Optional[rpc.Connection] = None
        self._beat_task: Optional[asyncio.Task] = None
        # Stop flag alongside cancellation, like the real daemons'
        # `while not self._shutdown` loops: on 3.10, asyncio.wait_for
        # can SWALLOW a cancellation that races the inner future's
        # completion (bpo-37658 family) — under a saturated GCS whose
        # replies arrive in bursts that race is routinely hit at fleet
        # size, and a swallowed cancel would leave a bare `while True`
        # beat loop immortal (stop() then hangs on awaiting it).
        self._stopping = False
        self._peer_addrs: List[str] = []
        # Outcomes the soak asserts on:
        self.reg_latency_s: float = -1.0
        self.heartbeats_sent = 0
        self.heartbeats_rejected = 0
        self.errors: List[str] = []
        self.drain_requests = 0

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        self.server = rpc.RpcServer({
            "ping": lambda conn, p: {"pong": True, "t1": clocks.wall(),
                                     "t2": clocks.wall()},
            "drain": self._h_drain,
            "shutdown": lambda conn, p: True,
        }, name=f"sim-agent-{self.index}")
        self.address = await self.server.start_tcp("127.0.0.1", 0)
        t0 = time.monotonic()
        if self.session_dir:
            from . import protocol
            self.gcs = rpc.ReconnectingConnection(
                self.gcs_address, name=f"sim{self.index}->gcs",
                on_reconnect=self._register,
                resolver=lambda: protocol.resolve_gcs_address(
                    self.session_dir, fallback=self.gcs_address))
            await self.gcs.ensure()
        else:
            self.gcs = await rpc.connect(self.gcs_address,
                                         name=f"sim{self.index}->gcs")
            await self._register(self.gcs)
        self.reg_latency_s = time.monotonic() - t0

    async def _register(self, conn) -> None:
        reply = await conn.call("register_node", {
            "node_id": self.node_id,
            "address": list(self.address),
            "resources": self.resources,
            "labels": {"sim": "1"},
            "store_path": "",
            "session_dir": self.session_dir or "",
            "view": False,          # the slim O(1) registration reply
        }, timeout=30)
        self.reregistrations += 1
        if not isinstance(reply, dict) or "num_nodes" not in reply:
            self.errors.append(f"unexpected register reply: {reply!r}")
        elif isinstance(reply.get("cluster_epoch"), int):
            self.last_epoch = max(self.last_epoch, reply["cluster_epoch"])

    async def _h_drain(self, conn, p):
        self.drain_requests += 1
        return True

    def set_peers(self, peer_addrs: List[str]) -> None:
        """Addresses ("host:port") this node pretends to observe, so
        heartbeats carry peer_stats and the GCS folds fleet-width
        evidence every tick (the path the cached addr index serves)."""
        self._peer_addrs = list(peer_addrs)

    def start_beating(self) -> None:
        self._beat_task = asyncio.ensure_future(self._beat_loop())

    async def _beat_loop(self) -> None:
        await asyncio.sleep(self.period_s * self._rng.random())
        while not self._stopping:
            t0 = time.monotonic()
            try:
                ok = await self.gcs.call("report_resources", {
                    "node_id": self.node_id,
                    "available": self.resources,
                    "peer_stats": {
                        a: {"rtt": 0.001 + self._rng.random() * 0.001,
                            "rate": 5e8, "age_s": 0.0}
                        for a in self._peer_addrs},
                    "transfer": {"bytes_served": 0, "bytes_pulled": 0},
                    "runtime": {"lease_queue_depth": 0.0},
                }, timeout=30)
                self.heartbeats_sent += 1
                if ok is False:
                    self.heartbeats_rejected += 1
                elif isinstance(ok, dict) and \
                        isinstance(ok.get("cluster_epoch"), int):
                    self.last_epoch = max(self.last_epoch,
                                          ok["cluster_epoch"])
                self._flush_telemetry()
            except asyncio.CancelledError:
                raise
            except Exception as e:   # noqa: BLE001 — recorded, asserted on
                self.errors.append(f"heartbeat: {type(e).__name__}: {e}")
            dt = time.monotonic() - t0
            await asyncio.sleep(max(0.0, self.period_s - dt))

    def _flush_telemetry(self) -> None:
        """The PR-7 batching discipline an agent follows: recorder rows
        and a metric snapshot ride the heartbeat tick as notifies."""
        now = time.time()
        rows = [{"task_id": os.urandom(8), "event": "soak", "ts": now,
                 "node_id": self.node_id}
                for _ in range(self.telemetry_rows)]
        self.gcs.notify("task_events", {
            "blob": rpc._pack(rows), "n": len(rows),
            "src": self.node_id, "dropped": 0})
        self.gcs.notify("report_metrics", {
            "worker_id": self.node_id,
            "metrics": [{"name": f"ray_tpu_soak_gauge_{i}",
                         "labels": {"node_id": self.node_id.hex()},
                         "type": "gauge", "value": float(i)}
                        for i in range(self.metrics_rows)]})

    async def stop(self) -> None:
        self._stopping = True
        if self._beat_task is not None:
            self._beat_task.cancel()
            try:
                # Bounded even if the cancel was swallowed (see
                # _stopping): the flagged loop exits within one period
                # + the in-flight call's own 30s timeout.
                await asyncio.wait_for(self._beat_task,
                                       35.0 + self.period_s)
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self.gcs is not None and not self.gcs.closed:
            await self.gcs.close()
        if self.server is not None:
            await self.server.close()


async def run_soak(gcs_address: tuple, n_nodes: int, duration_s: float,
                   period_s: float = 0.25,
                   register_concurrency: int = 32,
                   log=None) -> Dict:
    """Register `n_nodes` simulated nodes in one wave, heartbeat for
    `duration_s`, and return the measured outcome dict (see keys below).
    Assertion thresholds belong to the caller; `log` (e.g. print) gets
    one line per stage."""
    log = log or (lambda *_: None)
    nodes = [SimulatedNode(gcs_address, i, period_s=period_s)
             for i in range(n_nodes)]
    t_wave = time.monotonic()
    await rpc.gather_windowed(
        lambda i: nodes[i].start(), range(n_nodes),
        window=register_concurrency)
    wave_s = time.monotonic() - t_wave
    log(f"registered {n_nodes} nodes in {wave_s:.2f}s")
    # Every node observes a couple of ring neighbours: fleet-width
    # peer-stats folding on every heartbeat.
    addrs = [f"{n.address[0]}:{n.address[1]}" for n in nodes]
    for i, n in enumerate(nodes):
        n.set_peers([addrs[(i + 1) % n_nodes], addrs[(i + 2) % n_nodes]])
    for n in nodes:
        n.start_beating()

    # Control probe: a separate connection sampling GCS responsiveness
    # (kv round trips) through the soak — an O(N) stall on the GCS main
    # loop shows up here as a latency spike, which is the measurable
    # form of "no O(N) per-tick work left on the main loop".
    probe = await rpc.connect(tuple(gcs_address), name="soak-probe")
    probe_lat: List[float] = []
    # Flag alongside cancellation, same reason as SimulatedNode._stopping:
    # a 3.10 wait_for can swallow the cancel, and a bare `while True`
    # loop would then be immortal.
    probe_stop = False

    async def _probe_loop():
        while not probe_stop:
            t0 = time.monotonic()
            try:
                await probe.call("kv_get", {"ns": "soak", "key": "probe"},
                                 timeout=30)
                probe_lat.append(time.monotonic() - t0)
            except asyncio.CancelledError:
                raise
            except Exception:   # noqa: BLE001 — surfaced via sample count
                pass
            await asyncio.sleep(0.05)

    probe_task = asyncio.ensure_future(_probe_loop())
    await asyncio.sleep(duration_s)
    log(f"soak phase done ({duration_s}s)")

    # Steady-state delta poll: with nothing changing, a since-query must
    # return (near-)zero views — the "broadcasts are deltas" assertion.
    full = await probe.call("get_nodes", {"since": -1}, timeout=30)
    epoch = full["epoch"]
    await asyncio.sleep(max(2.0, 3 * period_s))
    delta = await probe.call("get_nodes", {"since": epoch}, timeout=30)
    metrics = await probe.call("get_metrics", {}, timeout=60)
    log(f"queries done (delta={len(delta['changed'])})")

    probe_stop = True
    probe_task.cancel()
    try:
        await asyncio.wait_for(probe_task, 35.0)
    except (asyncio.CancelledError, Exception):  # noqa: BLE001
        pass
    await probe.close()
    for batch_start in range(0, n_nodes, 64):
        await asyncio.gather(
            *[n.stop() for n in nodes[batch_start:batch_start + 64]])
    log("stopped")

    by_name = {}
    for m in metrics:
        by_name.setdefault(m["name"], []).append(m)
    reg = [n.reg_latency_s for n in nodes]
    alive = sum(1 for v in full["changed"] if v["alive"])
    return {
        "nodes": n_nodes,
        "wave_s": wave_s,
        "reg_p50_s": percentile(reg, 50),
        "reg_p99_s": percentile(reg, 99),
        "heartbeats_sent": sum(n.heartbeats_sent for n in nodes),
        "heartbeats_rejected": sum(n.heartbeats_rejected for n in nodes),
        "drain_requests": sum(n.drain_requests for n in nodes),
        "errors": [e for n in nodes for e in n.errors],
        "alive_at_end": alive,
        "delta_changed": len(delta["changed"]),
        "delta_total": delta["total"],
        "probe_p50_s": percentile(probe_lat, 50),
        "probe_p99_s": percentile(probe_lat, 99),
        "probe_samples": len(probe_lat),
        "gcs_dropped_rows": next(
            (m[0]["value"] for k, m in by_name.items()
             if k == "ray_tpu_gcs_task_events_dropped_total"), None),
        "soak_metric_series": sum(
            len(v) for k, v in by_name.items()
            if k.startswith("ray_tpu_soak_gauge_")),
        "gcs_loop_busy": {
            tuple(sorted(m["labels"].items())): m["value"]
            for m in by_name.get("ray_tpu_daemon_loop_busy_ratio", [])
            if m["labels"].get("daemon") == "gcs"},
    }
