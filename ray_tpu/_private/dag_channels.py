"""Compiled-DAG channel plane: agent-side ring management + cross-node
bridges.

Same-node compiled-graph edges are futex rings inside the shared arena
(`shm_store.Channel` over store.cc `rts_chan_*`) — two futex wakes and a
memcpy per hop, no RPC at all.  When an edge spans nodes, compilation
splits it into a HOME ring on the producer's node and a MIRROR ring on
each consumer node, stitched by an agent-resident bridge:

    producer ──home ring──> [bridge thread @ source agent]
        ──call_with_raw("dag_chan_write") over the native framer──>
    [dest agent] ──mirror ring──> consumers

The bridge reads the home ring as one of its registered readers (so ring
backpressure includes the wire leg), ships the message body as a raw
out-of-band frame (vectored writev on the native framer — no msgpack of
the payload), and the destination agent's write blocks while the mirror
ring is full, which stalls the bridge's call, which stalls the home
ring, which blocks the producer: backpressure is end-to-end without any
credit protocol.  Per steady-state step the only traffic is ONE
agent→agent data frame per cross-node edge — no GCS, no owner
bookkeeping, no leases (reference: compiled graphs registering channel
readers/writers once at compile time, compiled_dag_node.py:805).

EOF and failure propagate the same way values do: a closed home ring
makes the bridge forward `dag_chan_close` to the mirror; an unreachable
destination makes the bridge close the home ring, cascading EOF to every
endpoint of the pipeline (the driver surfaces it as a typed
DAGBrokenError).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional, Tuple

from . import rpc
from .shm_store import Channel, ChannelClosed
from ..dag import _transport

logger = logging.getLogger("ray_tpu.dag.channels")


def _mint_spill_id() -> bytes:
    # Spilled ring messages are raw arena objects addressed only through
    # the ring that carries their id — never owned, never tracked by an
    # owner — so a random id is sufficient (and collision-safe at 160
    # bits).
    return os.urandom(20)


class DagChannelManager:
    """Owns the compiled-graph rings created on THIS node by remote
    compilers, and the bridge threads pumping cross-node edges out of
    local home rings.  One instance per agent; all handlers are
    registered into the agent's RPC handler table under dag_*."""

    def __init__(self, store):
        self.store = store
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # chan_id -> (Channel, nreaders, slot_bytes) for rings this agent
        # created (it holds their creator pins until dag_chan_destroy).
        self._created: Dict[bytes, Tuple[Channel, int, int]] = {}
        # chan_id -> per-DAG spill-id prefix (mirror writes mint under
        # it so the destroy-time orphan sweep can reclaim them).
        self._spill_prefixes: Dict[bytes, bytes] = {}
        # (chan_id, reader) -> _Bridge
        self._bridges: Dict[Tuple[bytes, int], "_Bridge"] = {}
        self._conns: Dict[tuple, rpc.Connection] = {}
        # Dedicated pool for mirror-ring writes: a write blocks while the
        # ring is full (that IS the backpressure), and parking those on
        # the daemon's shared default executor could starve spill I/O.
        self._write_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="dagchan")
        self._shutdown = False

    def handlers(self) -> dict:
        return {
            "dag_chan_create": self.h_chan_create,
            "dag_chan_write": self.h_chan_write,
            "dag_chan_close": self.h_chan_close,
            "dag_chan_destroy": self.h_chan_destroy,
            "dag_bridge_start": self.h_bridge_start,
            "dag_bridge_stop": self.h_bridge_stop,
            "dag_chan_stats": self.h_chan_stats,
        }

    # -------------------------------------------------------- compile-time --
    async def h_chan_create(self, conn, p):
        """Create a ring in this node's arena on behalf of a remote
        compiler (the driver compiles once; this is compile-time traffic,
        exempt from the zero-RPC steady-state budget)."""
        chan = p["chan"]
        if chan in self._created:       # idempotent retry
            return True
        ch = Channel.create(self.store, chan, nslots=int(p["nslots"]),
                            slot_bytes=int(p["slot_bytes"]),
                            nreaders=int(p["nreaders"]))
        self._created[chan] = (ch, int(p["nreaders"]),
                               int(p["slot_bytes"]))
        if p.get("spill_prefix"):
            self._spill_prefixes[chan] = bytes(p["spill_prefix"])
        return True

    async def h_bridge_start(self, conn, p):
        key = (p["chan"], int(p["reader"]))
        if key in self._bridges:
            return True
        self._loop = asyncio.get_running_loop()
        br = _Bridge(self, chan=p["chan"], reader=int(p["reader"]),
                     dest_addr=tuple(p["dest_addr"]),
                     dest_chan=p["dest_chan"])
        self._bridges[key] = br
        br.start()
        return True

    # -------------------------------------------------------- steady state --
    async def h_chan_write(self, conn, p):
        """Bridge ingress: one message body into a local mirror ring.
        The reply is deliberately withheld until the ring accepted the
        message — a full mirror stalls the sender end-to-end."""
        body = await conn.take_raw(p["raw_id"])
        ent = self._created.get(p["chan"])
        if ent is None:
            return {"err": "unknown_channel"}
        ch, nreaders, slot_bytes = ent
        prefix = self._spill_prefixes.get(p["chan"])
        mint = _transport.mint_for(prefix) if prefix else _mint_spill_id
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                self._write_pool,
                lambda: _transport.send(self.store, ch, body,
                                        nreaders, slot_bytes, mint,
                                        timeout_ms=600_000))
        except ChannelClosed:
            return {"err": "closed"}
        except Exception as e:  # noqa: BLE001 — typed to the bridge
            return {"err": f"{type(e).__name__}: {e}"}
        return True

    # ------------------------------------------------------------ teardown --
    async def h_chan_close(self, conn, p):
        ent = self._created.get(p["chan"])
        if ent is None:
            return False
        ent[0].close()
        return True

    async def h_chan_destroy(self, conn, p):
        """Drain leftover spill pins, then destroy the ring (drops the
        creator pin).  Quiescence is the caller's contract: the driver
        destroys only after every serve loop and bridge has exited."""
        ent = self._created.pop(p["chan"], None)
        if ent is None:
            return False
        ch, _, _ = ent
        prefix = self._spill_prefixes.pop(p["chan"], None)

        def _destroy():
            _transport.destroy_quiescent(self.store, ch)
            if prefix:
                # Quiescent by the caller's contract: any surviving
                # object under this DAG's prefix (writer killed before
                # its ring write landed) is garbage on this arena.
                _transport.sweep_orphan_spills(self.store, prefix)

        await asyncio.get_running_loop().run_in_executor(
            self._write_pool, _destroy)
        return True

    async def h_bridge_stop(self, conn, p):
        stopped = []
        for chan in p.get("chans", []):
            for key, br in list(self._bridges.items()):
                if key[0] == chan:
                    br.stop()
                    self._bridges.pop(key, None)
                    stopped.append(br)
        if stopped:
            # Join before acking: the caller destroys the home ring next,
            # and a bridge still inside a futex peek on it would read
            # recycled arena memory.  Bounded — the recv poll is 1s; a
            # bridge stuck in a forward call is woken when the mirror
            # ring's own destroy closes it.
            def _join():
                for br in stopped:
                    br.join(timeout=5)

            await asyncio.get_running_loop().run_in_executor(
                self._write_pool, _join)
        return True

    async def h_chan_stats(self, conn, p):
        ent = self._created.get(p["chan"])
        if ent is None:
            return None
        return ent[0].stats()

    def stop_all(self) -> None:
        self._shutdown = True
        for br in self._bridges.values():
            br.stop()
        self._bridges.clear()
        self._write_pool.shutdown(wait=False)

    # ------------------------------------------------------------- helpers --
    async def _dest_conn(self, addr: tuple) -> rpc.Connection:
        conn = self._conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(addr, name="agent->dagbridge",
                                     retries=2)
            self._conns[addr] = conn
        return conn


class _Bridge(threading.Thread):
    """Pumps one home-ring reader to one remote mirror ring.  A plain
    daemon thread: channel reads are blocking futex waits, which must
    never park the agent's event loop.  One message in flight (the
    forward call's reply IS the flow-control window)."""

    def __init__(self, mgr: DagChannelManager, *, chan: bytes, reader: int,
                 dest_addr: tuple, dest_chan: bytes):
        super().__init__(daemon=True,
                         name=f"dagbridge-{chan[:4].hex()}r{reader}")
        self._mgr = mgr
        self._chan = chan
        self._reader = reader
        self._dest_addr = dest_addr
        self._dest_chan = dest_chan
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    async def _forward(self, body: bytes):
        conn = await self._mgr._dest_conn(self._dest_addr)
        return await conn.call_with_raw(
            "dag_chan_write", {"chan": self._dest_chan},
            rpc.RawPayload([body]), timeout=620)

    def _notify_dest_close(self) -> None:
        async def _close():
            try:
                conn = await self._mgr._dest_conn(self._dest_addr)
                conn.notify("dag_chan_close", {"chan": self._dest_chan})
            except Exception:
                pass        # destination already gone
        try:
            asyncio.run_coroutine_threadsafe(
                _close(), self._mgr._loop).result(timeout=10)
        except Exception:
            pass

    def run(self) -> None:
        store = self._mgr.store
        try:
            ch = Channel.attach(store, self._chan)
        except Exception:
            logger.exception("bridge: attach %s failed", self._chan.hex())
            return
        try:
            while not self._stop.is_set():
                try:
                    # View mode: a spilled body is forwarded straight out
                    # of the pinned arena region (the framer's writev
                    # consumes the view) — no host materialization on the
                    # bridge hop, for device payloads and big host blobs
                    # alike.  Released once the forward call returns.
                    body, release = _transport.recv_view(
                        store, ch, self._reader, timeout_ms=1000)
                except TimeoutError:
                    continue
                except ChannelClosed:
                    # Clean upstream EOF: cascade it across the wire.
                    self._notify_dest_close()
                    break
                try:
                    fut = asyncio.run_coroutine_threadsafe(
                        self._forward(body), self._mgr._loop)
                    res = fut.result(timeout=640)
                except Exception as e:  # noqa: BLE001
                    logger.warning("bridge %s: forward failed: %s",
                                   self._chan[:4].hex(), e)
                    res = None
                finally:
                    release()
                if res is not True:
                    # Destination unreachable or mirror closed: break the
                    # pipeline LOUDLY by closing the home ring — every
                    # endpoint (and the driver's fetch) sees EOF instead
                    # of hanging on a step that will never arrive.
                    ch.close()
                    self._notify_dest_close()
                    break
        except Exception:
            logger.exception("bridge %s crashed", self._chan[:4].hex())
        finally:
            ch.close()      # idempotent; releases this thread's attach pin
