"""Wire-protocol message shapes shared by driver, workers, agent, and GCS.

Plays the role of the reference's protobuf schemas (reference:
src/ray/protobuf/{common,gcs_service,core_worker,node_manager}.proto), but as
msgpack-friendly plain dicts: the control plane is Python asyncio, so a
schema-compiler adds latency without type safety we can't get anyway. Field
names below are the single source of truth; every service cites these helpers.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

# Address = [host, port] (TCP) or "path" (unix socket); msgpack-safe.
Address = Any


def concat_parts(parts) -> bytes:
    """Join serialized parts (see serialization.py) into one bytes payload.
    bytes.join consumes buffer-protocol parts (memoryviews) directly, so
    this is a single-allocation single-pass copy — no per-part bytes()."""
    return b"".join(parts)


def function_id(pickled: bytes) -> bytes:
    return hashlib.sha1(pickled).digest()[:16]


def ref_locations(raw) -> List[Tuple]:
    """Normalize a ref entry's location hint (`e["ref"][2]`) to a list of
    address tuples, primary first.

    Accepts every shape that has ever been on the wire: None (no hint),
    a single `[host, port]` address (pre-directory peers published one
    primary), or the replica-directory list `[[host, port], ...]`."""
    if not raw:
        return []
    first = raw[0]
    if isinstance(first, (list, tuple)):
        return [tuple(a) for a in raw]
    return [tuple(raw)]


def make_task_spec(
    *,
    task_id: bytes,
    job_id: bytes,
    fn_id: bytes,
    args: List[dict],
    nreturns: int,
    owner_addr: Address,
    resources: Dict[str, float],
    retries_left: int = 0,
    actor_id: Optional[bytes] = None,
    method: Optional[str] = None,
    seq: int = 0,
    scheduling_strategy: Optional[dict] = None,
    runtime_env: Optional[dict] = None,
    name: str = "",
    streaming: Optional[dict] = None,
    deadline: Optional[float] = None,
) -> dict:
    """Equivalent of the reference's TaskSpecification (common/task/).

    args entries:
      {"v": bytes}                          — inline serialized value
      {"ref": [id_bytes, owner_addr, locations], "sz": nbytes}
                                            — by-reference
    `locations` is the owner's replica-directory snapshot at submit time
    (list of node addresses holding a copy, PRIMARY FIRST) or None when
    unknown; legacy peers sent a single address — use `ref_locations` to
    consume either shape.  `sz` (optional) is the serialized size of the
    referenced object: together they feed the locality-aware scheduler's
    bytes-already-local score and the agent's arg prefetch.
    """
    return {
        "task_id": task_id,
        "job_id": job_id,
        "fn_id": fn_id,
        "args": args,
        "nreturns": nreturns,
        "owner_addr": owner_addr,
        "resources": resources,
        "retries_left": retries_left,
        "actor_id": actor_id,
        "method": method,
        "seq": seq,
        "scheduling_strategy": scheduling_strategy,
        "runtime_env": runtime_env,
        "name": name,
        # {"bp": N} for streaming-generator tasks (num_returns="streaming");
        # absent/None for regular tasks.
        "streaming": streaming,
        # Absolute wall-clock (time.time()) end-to-end deadline from
        # .options(timeout_s=...), or None.  Travels with the spec across
        # every hop (driver -> agent -> worker -> nested submits) so the
        # remaining budget composes instead of stacking per-hop constants;
        # enforced owner-side (DeadlineExceededError on the return refs)
        # and checked worker-side before execution.
        "deadline": deadline,
        # {"trace_id", "span_id"} of the submitting span when tracing is
        # enabled (reference: remote_function.py:344 — tracing context
        # injected into every submit; workers chain execution spans to
        # it).  make_task_spec is the single choke point every task and
        # actor call flows through, so injection lives here.
        "trace": _trace_inject(),
    }


# --------------------------------------------------------------------------
# Pre-encoded spec prefixes (submit/complete fast path; see
# docs/control_plane.md).  A task spec splits into a STABLE prefix — every
# field that is constant across calls of one RemoteFunction / actor handle
# (fn_id, resources, owner, scheduling strategy, runtime env, job) — and a
# small per-call DELTA (task id, args, retries, seq, ...).  The prefix is
# msgpack-encoded ONCE and shipped as an opaque blob inside each
# submit_batch frame; the receiver decodes it once per batch (and caches
# the decode by blob), then reconstructs each spec as {**prefix, **delta}.
# This removes the per-call serialize/deserialize of the ~16 stable fields
# that dominated control-plane CPU under task fan-out.
# --------------------------------------------------------------------------

# Fields that may differ between two tasks sharing a prefix.  Everything
# else MUST be byte-identical across the batch (guaranteed by grouping:
# normal tasks batch per scheduling key + owner, actor tasks per handle).
SPEC_VOLATILE = ("retries_left", "nreturns", "streaming", "trace",
                 "method", "seq", "name", "deadline")


def spec_prefix_of(spec: dict) -> dict:
    """Normalize one sample spec into the stable prefix every delta is
    applied on top of: per-call fields reset to their cheapest defaults so
    a large inline arg (or a task id) can never be frozen into the blob."""
    p = dict(spec)
    p["task_id"] = b""
    p["args"] = []
    p["retries_left"] = 0
    p["seq"] = 0
    p["trace"] = None
    p["streaming"] = None
    p["deadline"] = None
    return p


def spec_delta(prefix: dict, spec: dict) -> dict:
    """Per-call wire delta: task id + args always, plus any volatile field
    that differs from the prefix.  {**prefix, **delta} == spec exactly."""
    d = {"task_id": spec["task_id"], "args": spec["args"]}
    for k in SPEC_VOLATILE:
        v = spec.get(k)
        if v != prefix.get(k):
            d[k] = v
    return d


def encode_prefix(prefix: dict) -> bytes:
    """Pack the stable prefix once; the blob is reused verbatim on every
    submit_batch frame (and is the receiver's decode-cache key)."""
    import msgpack
    return msgpack.packb(prefix, use_bin_type=True)


def decode_prefix(blob: bytes) -> dict:
    import msgpack
    return msgpack.unpackb(blob, raw=False, strict_map_key=False)


_tracing = None


def _trace_inject():
    # Module cached on first use (util.tracing has no _private imports, but
    # a top-level import would still cycle through ray_tpu/__init__): the
    # per-submit cost is one contextvar read.
    global _tracing
    if _tracing is None:
        from ..util import tracing as _t
        _tracing = _t
    return _tracing.inject()


def scheduling_key(fn_id: bytes, resources: Dict[str, float],
                   strategy: Optional[dict],
                   runtime_env: Optional[dict] = None) -> bytes:
    """Tasks with the same key can share leased workers (reference:
    NormalTaskSubmitter lease caching by SchedulingKey — which includes
    the runtime env, since envs shape the worker process)."""
    h = hashlib.sha1(fn_id)
    for k in sorted(resources):
        h.update(k.encode())
        h.update(str(resources[k]).encode())
    if strategy:
        h.update(repr(sorted(strategy.items())).encode())
    if runtime_env:
        from .runtime_env import runtime_env_hash
        h.update(runtime_env_hash(runtime_env))
    return h.digest()[:16]


# Actor states (reference: gcs.proto ActorTableData.ActorState)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

# Node states (reference: gcs.proto GcsNodeInfo.GcsNodeState + the
# autoscaler's DRAINING drain protocol, autoscaler.proto DrainNode).
# DRAINING is the two-phase departure state: the node finishes what it
# has (in-flight leases, actor hand-off, primary-object migration) but
# receives no new work; at the drain deadline it transitions to DEAD.
NODE_ALIVE = "ALIVE"
NODE_DRAINING = "DRAINING"
NODE_DEAD = "DEAD"

# Drain reasons (reference: autoscaler.proto DrainNodeReason —
# preemption carries a deadline the cloud enforces; idle drains come
# from the autoscaler; manual from operators/tests).
DRAIN_PREEMPTION = "preemption"
DRAIN_IDLE = "idle"
DRAIN_MANUAL = "manual"
# Gray-failure evacuation: the health scorer found the node alive but
# sustained-suspect (slow links, lossy NIC, asymmetric partition) and
# auto-triggered the drain — detect -> avoid -> evacuate.
DRAIN_GRAY = "gray"

# Pubsub channels (reference: pubsub channel types in gcs.proto)
CH_ACTOR = "actor"
CH_NODE = "node"
CH_ERROR = "error"
CH_LOG = "log"
CH_PG = "placement_group"

# Cluster epoch — the fencing token of GCS high availability
# (reference: Raft terms, Ongaro & Ousterhout; leader leases with
# monotonic epochs).  The epoch is a journaled monotonic integer bumped
# exactly once per failover, BEFORE the new primary serves a single
# request.  It is stamped into every lease grant, node registration and
# actor-placement decision under the EPOCH_KEY field; agents and core
# workers reject grants minted under an older epoch (StaleEpochError)
# and the primary rejects mutations carrying a stale one.  EPOCH_NONE
# marks a participant that has not yet learned any epoch (accepts the
# first one it sees).
EPOCH_KEY = "cluster_epoch"
EPOCH_NONE = 0

# Typed-rejection marker used on the wire when an agent refuses a
# stale-epoch lease operation: replies carry {"granted": False,
# "reject": REJECT_STALE_EPOCH, EPOCH_KEY: <current>} so owners can
# distinguish fencing from plain resource exhaustion.
REJECT_STALE_EPOCH = "stale_epoch"

# GCS high-availability files, all under the session dir (the shared
# path both the primary and the warm standby can reach):
#   GCS_ADDRESS_FILE — the ADVERTISED address: {"address": [h, p],
#     "cluster_epoch": e}.  Atomically replaced by whichever instance
#     currently holds the lease; every client re-reads it through
#     resolve_gcs_address() on every reconnect attempt, so failover
#     re-homing rides the existing jittered dial backoff.
#   GCS_LEASE_FILE — the primary's liveness lease: {"epoch",
#     "renewed" (wall), "ttl_s", "owner_pid", "address"}.  Renewed
#     every ttl/3 while the primary holds agent-heartbeat majority; a
#     standby takes over only once the lease has gone a full TTL
#     without renewal, and an ex-primary that observes a HIGHER epoch
#     in this file is fenced (refuses writes and exits).
#   GCS_STANDBY_FILE — the standby's tail progress: {"lag_bytes",
#     "ts", "pid"}; the primary exports it as the standby-lag gauges.
GCS_ADDRESS_FILE = "gcs_address.json"
GCS_LEASE_FILE = "gcs_lease.json"
GCS_STANDBY_FILE = "gcs_standby.json"


def resolve_gcs_address(session_dir: Optional[str], fallback=None):
    """Current advertised GCS address for a session, or `fallback`.

    The ONE address-resolution helper every reconnect path routes
    through (agents, core workers, drivers): reading the session's
    address file at dial time — instead of trusting the address cached
    from init()/argv forever — is what lets a failover (or a plain
    address change) re-home clients without process restarts."""
    if session_dir:
        import json
        import os
        try:
            with open(os.path.join(session_dir, GCS_ADDRESS_FILE)) as f:
                info = json.load(f)
            addr = info.get("address")
            if addr and len(addr) >= 2:
                return (addr[0], int(addr[1]))
        except (OSError, ValueError, TypeError):
            pass
    return fallback
