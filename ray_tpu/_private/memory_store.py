"""In-process memory store for small objects owned by this worker.

Equivalent of the reference's CoreWorkerMemoryStore (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.cc): task
returns at or under `max_direct_call_object_size` are sent inline in the
task reply and land here, keeping the shared-memory store and the agent off
the hot path. Supports async waiters so `get` (and owner-served
`fetch_object` RPCs from borrowers) can block until a pending task finishes.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple


class _Entry:
    __slots__ = ("data", "is_exception", "plasma_node")

    def __init__(self, data: Optional[bytes], is_exception: bool = False,
                 plasma_node=None):
        self.data = data              # serialized payload, None if in plasma
        self.is_exception = is_exception
        self.plasma_node = plasma_node  # node address holding primary copy


class MemoryStore:
    def __init__(self):
        self._objects: Dict[bytes, _Entry] = {}
        self._waiters: Dict[bytes, List[asyncio.Event]] = {}

    def put_inline(self, object_id: bytes, data: bytes, is_exception=False):
        self._objects[object_id] = _Entry(data, is_exception)
        self._wake(object_id)

    def put_plasma_location(self, object_id: bytes, node_addr):
        self._objects[object_id] = _Entry(None, plasma_node=node_addr)
        self._wake(object_id)

    def _wake(self, object_id: bytes):
        for ev in self._waiters.pop(object_id, []):
            ev.set()

    def get(self, object_id: bytes) -> Optional[_Entry]:
        return self._objects.get(object_id)

    def contains(self, object_id: bytes) -> bool:
        return object_id in self._objects

    async def wait_for(self, object_id: bytes, timeout: float | None = None
                       ) -> Optional[_Entry]:
        entry = self._objects.get(object_id)
        if entry is not None:
            return entry
        ev = asyncio.Event()
        self._waiters.setdefault(object_id, []).append(ev)
        try:
            if timeout is None:
                await ev.wait()
            else:
                await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            lst = self._waiters.get(object_id)
            if lst and ev in lst:
                lst.remove(ev)
        return self._objects.get(object_id)

    def delete(self, object_id: bytes):
        self._objects.pop(object_id, None)

    def size(self) -> int:
        return len(self._objects)
