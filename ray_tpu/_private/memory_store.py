"""In-process memory store for small objects owned by this worker.

Equivalent of the reference's CoreWorkerMemoryStore (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.cc): task
returns at or under `max_direct_call_object_size` are sent inline in the
task reply and land here, keeping the shared-memory store and the agent off
the hot path. Supports async waiters so `get` (and owner-served
`fetch_object` RPCs from borrowers) can block until a pending task finishes.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Dict, List, Optional, Tuple


class _Entry:
    __slots__ = ("data", "is_exception", "plasma_node", "size",
                 "secondaries", "device_nodes", "disk_nodes")

    def __init__(self, data, is_exception: bool = False,
                 plasma_node=None, size=None):
        # Serialized payload (None if in plasma).  Any bytes-like object:
        # raw-frame landings and zero-copy readers keep memoryviews here
        # end-to-end; producers that must cross a msgpack boundary
        # normalize there, not on insert (see core_worker.h_get_object).
        self.data = data
        self.is_exception = is_exception
        self.plasma_node = plasma_node  # node address holding primary copy
        # Serialized size in bytes when known (plasma entries stamp it at
        # put/return time): feeds the locality-aware scheduler's
        # bytes-already-local score via task-spec hints.
        self.size = size
        # Replica directory (owner-side; reference: the ownership table
        # tracks ALL locations of each object, ownership NSDI'21 §4):
        # agent addresses holding a pulled/partial SECONDARY copy, in
        # registration order.  Secondaries are evictable caches — the
        # holders deregister on eviction/drain, and frees broadcast to
        # them — so an entry here is a hint, never a liveness promise;
        # readers probe (or fail over) exactly as they do for the
        # primary.  None until the first registration (cheap common
        # case: most objects are never pulled anywhere).
        self.secondaries = None
        # DEVICE-TIER directory (device-direct data plane): nodes whose
        # workers hold this object's device arrays RESIDENT on their
        # accelerators (put/get of a value containing jax.Arrays
        # registers here).  Strictly a SCHEDULING hint — device bytes
        # live in process memory, not in any arena, so these addresses
        # are never valid pull sources and stay out of locations().
        self.device_nodes = None
        # STORAGE-TIER directory (tiered cluster memory): nodes holding a
        # SPILLED copy of this object (local NVMe spill file or an
        # external URI they can re-materialize).  Unlike the device tier
        # these ARE real restore sources — a holder's agent serves pulls
        # straight from the spill file (fetch_chunk's pread path) and
        # restores direct-to-arena via read_file_into — but they rank
        # BELOW arena holders for locality (DISK_TIER_WEIGHT): reading
        # NVMe beats a network pull, loses to bytes already mapped.
        self.disk_nodes = None

    def locations(self):
        """All known holders, primary first.  List of address tuples.
        Device-tier holders are deliberately excluded — they are
        scheduling hints, not pullable replicas (see device_locations).
        Disk-tier holders ARE included (last): their agents serve pulls
        from the spill file even when the arena copy is gone."""
        out = []
        if self.plasma_node is not None:
            out.append(tuple(self.plasma_node))
        if self.secondaries:
            out.extend(a for a in self.secondaries if a not in out)
        if self.disk_nodes:
            out.extend(a for a in self.disk_nodes if a not in out)
        return out


class MemoryStore:
    def __init__(self):
        self._objects: Dict[bytes, _Entry] = {}
        self._waiters: Dict[bytes, List[asyncio.Event]] = {}
        # Group waiters: [future, remaining_count] shared across many ids,
        # so a get() on N pending refs costs ONE future instead of N
        # Event+Task pairs (the reference amortizes the same way in C++ —
        # GetAsync callbacks on a single request context).
        self._gwaiters: Dict[bytes, List[list]] = {}
        # Cross-THREAD waiters (concurrent.futures, resolved directly from
        # _wake on the loop thread): a sync get() on one pending owned ref
        # parks its calling thread here instead of round-tripping a
        # coroutine through call_soon_threadsafe — saves a self-pipe wake,
        # a Task, and a gather per call on the 1:1 sync hot path
        # (reference: the Cython get blocks on a C++ future the same way).
        self._sync_waiters: Dict[bytes, list] = {}
        self._sync_lock = threading.Lock()

    def put_inline(self, object_id: bytes, data, is_exception=False):
        """`data` is any bytes-like (bytes / bytearray / memoryview) —
        stored as given, zero-copy."""
        self._objects[object_id] = _Entry(data, is_exception)
        self._wake(object_id)

    def put_plasma_location(self, object_id: bytes, node_addr,
                            size: int | None = None):
        self._objects[object_id] = _Entry(None, plasma_node=node_addr,
                                          size=size)
        self._wake(object_id)

    # ----------------------------------------------- replica directory ---
    def add_location(self, object_id: bytes, addr, *,
                     primary: bool = False,
                     device: bool = False,
                     disk: bool = False,
                     max_secondaries: int = 8) -> bool:
        """Register `addr` as a holder of a plasma object.  primary=True
        repoints the primary record (drain adoption); otherwise the addr
        joins the secondary set (bounded, oldest registration dropped —
        secondaries are evictable caches, so dropping a directory entry
        only costs a source, never correctness).  device=True records a
        DEVICE-TIER holder instead: a node whose workers keep the
        object's arrays resident on accelerators — a locality-scheduling
        signal, never a pull source.  disk=True records a STORAGE-TIER
        holder: the node spilled its copy to NVMe/external, and its
        agent can still serve pulls and restores from the file — a real
        source scored between arena-local and peer-arena by the
        locality scheduler."""
        entry = self._objects.get(object_id)
        if entry is None:
            return False
        if disk:
            addr = tuple(addr)
            if entry.disk_nodes is None:
                entry.disk_nodes = []
            if addr not in entry.disk_nodes:
                entry.disk_nodes.append(addr)
                while len(entry.disk_nodes) > max_secondaries:
                    entry.disk_nodes.pop(0)
            return True
        if device:
            addr = tuple(addr)
            if entry.device_nodes is None:
                entry.device_nodes = []
            if addr not in entry.device_nodes:
                entry.device_nodes.append(addr)
                while len(entry.device_nodes) > max_secondaries:
                    entry.device_nodes.pop(0)
            return True
        if entry.data is not None and not primary:
            return False
        addr = tuple(addr)
        if primary:
            if entry.secondaries and addr in entry.secondaries:
                entry.secondaries.remove(addr)
            entry.plasma_node = list(addr)
            return True
        if entry.plasma_node is not None and \
                tuple(entry.plasma_node) == addr:
            return True            # already the primary
        if entry.secondaries is None:
            entry.secondaries = []
        if addr in entry.secondaries:
            return True
        entry.secondaries.append(addr)
        while len(entry.secondaries) > max_secondaries:
            entry.secondaries.pop(0)
        return True

    def remove_location(self, object_id: bytes, addr, *,
                        disk: bool = False) -> None:
        """Deregister a holder.  disk=True retracts ONLY the storage-tier
        marking (the node restored its spill file back into the arena —
        its primary/secondary record, if any, stands); otherwise the
        addr leaves both the secondary set and the disk tier (the holder
        dropped the bytes entirely)."""
        entry = self._objects.get(object_id)
        if entry is None:
            return
        addr = tuple(addr)
        if entry.disk_nodes and addr in entry.disk_nodes:
            entry.disk_nodes.remove(addr)
        if disk:
            return
        if entry.secondaries and addr in entry.secondaries:
            entry.secondaries.remove(addr)

    def locations(self, object_id: bytes):
        """All known holders of a plasma object, primary first ([] for
        absent/inline entries)."""
        entry = self._objects.get(object_id)
        return entry.locations() if entry is not None else []

    def device_locations(self, object_id: bytes):
        """Device-tier holders (nodes with the arrays accelerator-
        resident): scheduling hints only, never pull sources."""
        entry = self._objects.get(object_id)
        if entry is None or not entry.device_nodes:
            return []
        return list(entry.device_nodes)

    def disk_locations(self, object_id: bytes):
        """Storage-tier holders (nodes whose copy lives in a spill file):
        real restore sources, ranked below arena holders for locality."""
        entry = self._objects.get(object_id)
        if entry is None or not entry.disk_nodes:
            return []
        return list(entry.disk_nodes)

    def _wake(self, object_id: bytes):
        for ev in self._waiters.pop(object_id, []):
            ev.set()
        if self._sync_waiters:
            with self._sync_lock:
                sw = self._sync_waiters.pop(object_id, None)
            if sw:
                for f in sw:
                    if not f.done():
                        f.set_result(True)
        gw = self._gwaiters.pop(object_id, None)
        if gw:
            entry = self._objects.get(object_id)
            errored = entry is not None and entry.is_exception
            for w in gw:
                w[1] -= 1
                if (w[1] <= 0 or errored) and not w[0].done():
                    # An error entry completes the whole batch early: the
                    # caller surfaces it without waiting for the rest
                    # (matching gather's raise-on-first-error semantics).
                    w[0].set_result(True)

    def get(self, object_id: bytes) -> Optional[_Entry]:
        return self._objects.get(object_id)

    def add_sync_waiter(self, object_id: bytes
                        ) -> Optional[concurrent.futures.Future]:
        """Register a cross-thread waiter for a pending object, or return
        None when the object is already present (caller re-reads).

        Safe against the lock-free `if self._sync_waiters:` pre-check in
        _wake: that check can run while this thread is mid-registration
        (dict still empty) and skip the pop — so after registering we
        re-check _objects and, if the entry landed meanwhile, withdraw the
        future and let the caller read directly.  Entries land in _objects
        BEFORE _wake runs, so one of the two sides always sees the
        other."""
        with self._sync_lock:
            if object_id in self._objects:
                return None
            f: concurrent.futures.Future = concurrent.futures.Future()
            self._sync_waiters.setdefault(object_id, []).append(f)
        if object_id in self._objects:
            # Entry landed during registration; _wake may have missed us.
            self.discard_sync_waiter(object_id, f)
            return None
        return f

    def discard_sync_waiter(self, object_id: bytes, fut) -> None:
        with self._sync_lock:
            lst = self._sync_waiters.get(object_id)
            if lst is not None:
                if fut in lst:
                    lst.remove(fut)
                if not lst:
                    del self._sync_waiters[object_id]

    def contains(self, object_id: bytes) -> bool:
        return object_id in self._objects

    async def wait_for(self, object_id: bytes, timeout: float | None = None
                       ) -> Optional[_Entry]:
        entry = self._objects.get(object_id)
        if entry is not None:
            return entry
        ev = asyncio.Event()
        self._waiters.setdefault(object_id, []).append(ev)
        try:
            if timeout is None:
                await ev.wait()
            else:
                await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            lst = self._waiters.get(object_id)
            if lst and ev in lst:
                lst.remove(ev)
        return self._objects.get(object_id)

    async def wait_for_many(self, object_ids, timeout: float | None = None
                            ) -> bool:
        """Block until every id is present, with ONE future regardless of
        how many are pending. Returns False on timeout. Loop-thread only
        (same constraint as wait_for)."""
        objects = self._objects
        missing = [o for o in object_ids if o not in objects]
        if not missing:
            return True
        fut = asyncio.get_running_loop().create_future()
        w = [fut, len(missing)]
        gw = self._gwaiters
        for o in missing:
            gw.setdefault(o, []).append(w)
        try:
            if timeout is None:
                await fut
            else:
                await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            if w[1] > 0:
                # Timed out / cancelled with ids still pending: unregister.
                for o in missing:
                    lst = gw.get(o)
                    if lst is not None and w in lst:
                        lst.remove(w)
                        if not lst:
                            del gw[o]

    def delete(self, object_id: bytes):
        self._objects.pop(object_id, None)

    def size(self) -> int:
        return len(self._objects)
