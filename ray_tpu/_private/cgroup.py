"""ctypes binding to the native cgroup v2 manager (src/cgroup).

Reference: src/ray/common/cgroup2/cgroup_manager.h — workers live in a
framework cgroup so the kernel bounds their memory/cpu.  Disabled by
default (config `cgroup_enabled`); every operation degrades to a no-op
when cgroup2 is unavailable or read-only (the common container case).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional

from . import native_build

logger = logging.getLogger("ray_tpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "cgroup", "cgroup_manager.cc")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_cgroup.so")

_build_lock = threading.Lock()
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        native_build.build_so(_SRC, _SO, fallback_to_stale=True)
        lib = ctypes.CDLL(_SO)
        lib.cg_available.restype = ctypes.c_int
        lib.cg_create.argtypes = [ctypes.c_char_p]
        lib.cg_create.restype = ctypes.c_int
        lib.cg_set_memory_max.argtypes = [ctypes.c_char_p,
                                          ctypes.c_longlong]
        lib.cg_set_memory_max.restype = ctypes.c_int
        lib.cg_set_cpu_weight.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.cg_set_cpu_weight.restype = ctypes.c_int
        lib.cg_add_pid.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.cg_add_pid.restype = ctypes.c_int
        lib.cg_remove.argtypes = [ctypes.c_char_p]
        lib.cg_remove.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    try:
        return bool(_load().cg_available())
    except Exception:
        return False


class WorkerCgroup:
    """One cgroup holding a node's worker processes (reference: the
    'application' half of the system/application split)."""

    def __init__(self, name: str = "ray_tpu_workers",
                 memory_max: Optional[int] = None,
                 cpu_weight: Optional[int] = None):
        self.name = name.encode()
        self.active = False
        if not available():
            return
        lib = _load()
        if lib.cg_create(self.name) != 0:
            return
        self.active = True
        if memory_max is not None:
            lib.cg_set_memory_max(self.name, memory_max)
        if cpu_weight is not None:
            lib.cg_set_cpu_weight(self.name, cpu_weight)
        logger.info("worker cgroup %s active", name)

    def add(self, pid: int) -> bool:
        if not self.active:
            return False
        return _load().cg_add_pid(self.name, pid) == 0

    def close(self):
        if self.active:
            _load().cg_remove(self.name)
            self.active = False
