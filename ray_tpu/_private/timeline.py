"""Chrome-trace construction from GCS task events — shared by
ray_tpu.timeline() and the dashboard's /api/timeline (reference:
python/ray/_private/state.py:441 chrome_tracing_dump).

Clock alignment: task events are stamped on each node's OWN wall clock,
so a raw cross-node trace shows effects before causes (a remote RUNNING
"earlier" than its driver's SUBMITTED whenever the hosts disagree about
now — the artifact `ray timeline` exhibits at scale).  `align_events`
corrects every event into the GCS's reference frame using the per-node
offsets the health loop estimates (NTP-style; see clocks.py and
GcsServer._probe_node): corrected = ts - offset(node), since offset is
that node's clock MINUS the GCS's.  After correction, causality nests:
driver SUBMITTED strictly precedes remote RUNNING, transfer spans fall
inside their pull's start/commit span — up to the estimator's asymmetry
error bound, which node views carry alongside the offset.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def offsets_from_node_views(nodes: List[dict]) -> Dict[bytes, float]:
    """node_id -> clock offset (node wall minus GCS wall, seconds) from
    GCS node views; nodes without an estimate are treated as aligned."""
    out: Dict[bytes, float] = {}
    for n in nodes or []:
        off = n.get("clock_offset_s")
        if off:
            out[bytes(n["node_id"])] = float(off)
    return out


def align_events(raw: List[dict],
                 offsets: Optional[Dict[bytes, float]]) -> List[dict]:
    """Shallow-copied events with every timestamp field corrected into
    the GCS reference frame by its node's estimated offset."""
    if not offsets:
        return list(raw)
    out = []
    for e in raw:
        off = offsets.get(bytes(e.get("node_id") or b""))
        if not off:
            out.append(e)
            continue
        e = dict(e)
        e["ts"] = e["ts"] - off
        if "start_us" in e:
            e["start_us"] = e["start_us"] - int(off * 1e6)
        out.append(e)
    return out


def chrome_trace_events(raw: List[dict],
                        offsets: Optional[Dict[bytes, float]] = None
                        ) -> List[dict]:
    """Pair RUNNING → FINISHED/FAILED/CANCELLED per task into duration
    events; submit times become instant events; SPAN rows (tracing spans
    and flight-recorder plane spans) become complete events.  Pass
    `offsets` (node_id -> seconds, from offsets_from_node_views) to
    correct per-node clocks first.  Load the result in chrome://tracing
    or Perfetto."""
    # Submitter and executor flush on independent clocks, so sink order
    # is not event order; after alignment the sort is causal up to the
    # estimator's error bound.
    raw = sorted(align_events(raw, offsets), key=lambda e: e["ts"])
    starts: dict = {}
    events: list = []
    for e in raw:
        tid = e["task_id"]
        pid = e.get("node_id", b"").hex()[:8]
        wid = e.get("worker_id", b"").hex()[:8]
        if e["event"] == "RUNNING":
            starts[tid] = e
        elif e["event"] in ("FINISHED", "FAILED", "CANCELLED") \
                and tid in starts:
            s0 = starts.pop(tid)
            events.append({
                "name": s0.get("name") or tid.hex()[:8],
                "cat": "task", "ph": "X",
                "ts": s0["ts"] * 1e6,
                "dur": max(0.0, (e["ts"] - s0["ts"]) * 1e6),
                "pid": s0.get("node_id", b"").hex()[:8],
                "tid": s0.get("worker_id", b"").hex()[:8],
                "args": {"task_id": tid.hex(), "outcome": e["event"]},
            })
        elif e["event"] == "SUBMITTED":
            events.append({
                "name": f"submit:{e.get('name') or tid.hex()[:8]}",
                "cat": "submit", "ph": "i", "s": "t",
                "ts": e["ts"] * 1e6, "pid": pid, "tid": wid,
            })
        elif e["event"] == "SPAN":
            # Complete spans: tracing spans (util/tracing.py) carry
            # trace/span ids; flight-recorder plane spans (lease
            # lifecycle, object transfers) carry a category instead.
            # Both ride the same pipeline and render without external
            # collectors.
            cat = e.get("cat") or "trace"
            if cat == "anomaly":
                # Diagnosis-plane detector firings overlay the trace as
                # GLOBAL instant marks (full-height lines in Perfetto):
                # the hang/wedge is visible against the work around it.
                events.append({
                    "name": e.get("name") or "anomaly",
                    "cat": "anomaly", "ph": "i", "s": "g",
                    "ts": e.get("start_us", e["ts"] * 1e6),
                    "pid": pid, "tid": wid,
                    "args": dict(e.get("args") or {}),
                })
                continue
            args = {"trace_id": e.get("trace_id"),
                    "span_id": e.get("span_id"),
                    "parent_span_id": e.get("parent_span_id")} \
                if e.get("trace_id") else dict(e.get("args") or {})
            if tid:
                args.setdefault("id", tid.hex())
            nm = e.get("name") or tid.hex()[:8]
            events.append({
                "name": f"span:{nm}" if cat == "trace" else f"{cat}:{nm}",
                "cat": cat, "ph": "X",
                "ts": e.get("start_us", e["ts"] * 1e6),
                "dur": e.get("dur_us", 0),
                "pid": pid, "tid": wid,
                "args": args,
            })
        elif e["event"] == "PREFETCH":
            events.append({
                "name": "prefetch",
                "cat": "lease", "ph": "i", "s": "t",
                "ts": e["ts"] * 1e6, "pid": pid, "tid": wid,
                "args": {"task_id": tid.hex()},
            })
    return events
