"""Chrome-trace construction from GCS task events — shared by
ray_tpu.timeline() and the dashboard's /api/timeline (reference:
python/ray/_private/state.py:441 chrome_tracing_dump)."""

from __future__ import annotations

from typing import List


def chrome_trace_events(raw: List[dict]) -> List[dict]:
    """Pair RUNNING → FINISHED/FAILED/CANCELLED per task into duration
    events; submit times become instant events. Load the result in
    chrome://tracing or Perfetto."""
    # Submitter and executor flush on independent clocks, so sink order is
    # not event order — recorded timestamps are (same-host clocks).
    raw = sorted(raw, key=lambda e: e["ts"])
    starts: dict = {}
    events: list = []
    for e in raw:
        tid = e["task_id"]
        pid = e.get("node_id", b"").hex()[:8]
        wid = e.get("worker_id", b"").hex()[:8]
        if e["event"] == "RUNNING":
            starts[tid] = e
        elif e["event"] in ("FINISHED", "FAILED", "CANCELLED") \
                and tid in starts:
            s0 = starts.pop(tid)
            events.append({
                "name": s0.get("name") or tid.hex()[:8],
                "cat": "task", "ph": "X",
                "ts": s0["ts"] * 1e6,
                "dur": max(0.0, (e["ts"] - s0["ts"]) * 1e6),
                "pid": s0.get("node_id", b"").hex()[:8],
                "tid": s0.get("worker_id", b"").hex()[:8],
                "args": {"task_id": tid.hex(), "outcome": e["event"]},
            })
        elif e["event"] == "SUBMITTED":
            events.append({
                "name": f"submit:{e.get('name') or tid.hex()[:8]}",
                "cat": "submit", "ph": "i", "s": "t",
                "ts": e["ts"] * 1e6, "pid": pid, "tid": wid,
            })
        elif e["event"] == "SPAN":
            # Tracing spans (util/tracing.py): complete events carrying
            # the trace/span ids so cross-process causality is visible in
            # Perfetto without an external collector.
            events.append({
                "name": f"span:{e.get('name') or tid.hex()[:8]}",
                "cat": "trace", "ph": "X",
                "ts": e.get("start_us", e["ts"] * 1e6),
                "dur": e.get("dur_us", 0),
                "pid": pid, "tid": wid,
                "args": {"trace_id": e.get("trace_id"),
                         "span_id": e.get("span_id"),
                         "parent_span_id": e.get("parent_span_id")},
            })
    return events
