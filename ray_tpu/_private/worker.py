"""Global driver runtime: init/shutdown and the module-level API state.

Equivalent of the reference's driver layer (reference:
python/ray/_private/worker.py — global Worker at :438, init at :1432,
connect at :2460, shutdown at :2082).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import subprocess
from typing import Any, Dict, List, Optional

from . import node as node_mod
from .config import Config, get_config, set_config
from .core_worker import CoreWorker

logger = logging.getLogger("ray_tpu.worker")

# Well-known head-node address drop (reference: /tmp/ray/ray_current_cluster).
CLUSTER_ADDRESS_FILE = "/tmp/ray_tpu/ray_current_cluster"


def write_cluster_address_file(address: tuple):
    os.makedirs(os.path.dirname(CLUSTER_ADDRESS_FILE), exist_ok=True)
    with open(CLUSTER_ADDRESS_FILE, "w") as f:
        f.write(f"{address[0]}:{address[1]}")


def read_cluster_address_file():
    try:
        with open(CLUSTER_ADDRESS_FILE) as f:
            return f.read().strip() or None
    except OSError:
        return None


class Runtime:
    def __init__(self):
        self.core: Optional[CoreWorker] = None
        self.session_dir: Optional[str] = None
        self.procs: List[subprocess.Popen] = []
        self.gcs_address: Optional[tuple] = None
        self.is_external_cluster = False
        self.mode = "driver"


_runtime: Optional[Runtime] = None


def global_runtime() -> Runtime:
    if _runtime is None:
        raise RuntimeError(
            "ray_tpu.init() must be called before using the API")
    return _runtime


def is_initialized() -> bool:
    return _runtime is not None


def _set_global_from_existing(core: CoreWorker):
    """Install a Runtime for an already-connected core (worker processes)."""
    global _runtime
    rt = Runtime()
    rt.core = core
    rt.session_dir = core.session_dir
    rt.gcs_address = core.gcs_address
    rt.mode = "worker"
    _runtime = rt


def init(address: Optional[str] = None, *,
         num_cpus: Optional[int] = None,
         num_tpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         labels: Optional[Dict[str, str]] = None,
         _system_config: Optional[dict] = None,
         log_level: str = "WARNING") -> "Runtime":
    """Start (or connect to) a cluster.

    - no address: start a head node in this process's session — GCS + one
      node agent as subprocesses (reference: ray.init starting
      start_head_processes, node.py:1357)
    - address='host:port': connect to an existing GCS; the driver attaches
      to the agent on this machine (reference: ray.init(address=...)).
    """
    global _runtime
    if _runtime is not None:
        return _runtime
    logging.basicConfig(level=log_level)
    set_config(Config(_system_config))
    cfg = get_config()
    rt = Runtime()
    if address is None and os.environ.get("RAY_TPU_ADDRESS"):
        # Driver spawned under a submitted job (or user exported the
        # address): join that cluster (reference: RAY_ADDRESS).
        address = os.environ["RAY_TPU_ADDRESS"]
    address_was_auto = address == "auto"
    if address == "auto":
        address = read_cluster_address_file()
        if address is None:
            raise ConnectionError(
                "address='auto' but no running cluster was found "
                f"({CLUSTER_ADDRESS_FILE} missing); start one with "
                "`python -m ray_tpu start --head`")
    from . import auth
    if address is None:
        rt.session_dir = node_mod.new_session_dir()
        # Session token BEFORE any daemon spawns: children inherit it via
        # child_env() and their servers require it from birth.
        # write_wellknown=False: only `ray_tpu start --head` writes the
        # cluster address file, so only it may write the paired token drop.
        auth.ensure_cluster_token(rt.session_dir, write_wellknown=False)
        gcs_proc, gcs_addr = node_mod.start_gcs(
            rt.session_dir, system_config=_system_config)
        rt.procs.append(gcs_proc)
        store_cap = object_store_memory or _auto_store_bytes(cfg)
        res = node_mod.default_resources(num_cpus, num_tpus, resources)
        agent_proc, agent_addr, store_path, node_id = node_mod.start_agent(
            rt.session_dir, gcs_addr, res, labels=labels,
            store_capacity=store_cap, system_config=_system_config)
        rt.procs.append(agent_proc)
        rt.gcs_address = gcs_addr
    else:
        # Attaching driver: the cluster's token comes from the env, a
        # token file, or the well-known local drop — install it before
        # the first connect.  The drop is only trusted when the target
        # IS the local cluster it was written for: address='auto', or an
        # explicit address equal to the one in the cluster address file
        # (head start writes the pair together).  Any other explicit
        # address skips it — a stale token from an older local cluster
        # would produce opaque ConnectionLost failures instead of a
        # clear auth error.
        local_attach = address_was_auto or \
            address == read_cluster_address_file()
        tok = auth.install_process_token(
            allow_cluster_file=local_attach)
        if tok is None and not auth.auth_disabled():
            logger.warning(
                "no cluster auth token resolved for %s; connection will "
                "fail if the cluster requires one (set %s)", address,
                auth.TOKEN_ENV)
        host, port = address.rsplit(":", 1)
        rt.gcs_address = (host, int(port))
        rt.is_external_cluster = True
        # Find this machine's agent via the GCS node table.
        import asyncio
        from . import rpc as rpc_mod

        async def _find():
            conn = await rpc_mod.connect(rt.gcs_address)
            nodes = await conn.call("get_nodes", {})
            await conn.close()
            return nodes

        nodes = asyncio.run(_find())
        alive = [n for n in nodes if n["alive"]]
        if not alive:
            raise RuntimeError("no alive nodes in cluster")
        # Prefer a node that isn't mid-drain as the driver's home agent.
        n0 = ([n for n in alive if not n.get("draining")] or alive)[0]
        agent_addr = tuple(n0["address"])
        store_path = n0["store_path"]
        node_id = bytes(n0["node_id"])
        rt.session_dir = n0.get("session_dir") or node_mod.new_session_dir()

    core = CoreWorker(
        mode="driver", gcs_address=rt.gcs_address, agent_address=agent_addr,
        store_path=store_path, node_id=node_id, session_dir=rt.session_dir)
    core.start_driver()
    rt.core = core
    _runtime = rt
    from .usage import record_session
    record_session(core)
    atexit.register(shutdown)
    return rt


def _auto_store_bytes(cfg) -> int:
    if cfg.object_store_memory_bytes:
        return cfg.object_store_memory_bytes
    try:
        import psutil
        avail = psutil.virtual_memory().available
    except Exception:
        avail = 8 * 1024**3
    return int(min(avail * cfg.object_store_auto_fraction,
                   cfg.object_store_max_auto_bytes))


def shutdown():
    global _runtime
    rt = _runtime
    if rt is None:
        return
    _runtime = None
    if rt.core is not None:
        try:
            rt.core.shutdown()
        except Exception:
            pass
    for proc in reversed(rt.procs):
        try:
            proc.terminate()
        except ProcessLookupError:
            pass
    for proc in reversed(rt.procs):
        try:
            proc.wait(timeout=3)
        except subprocess.TimeoutExpired:
            proc.kill()
