"""External (cloud) spill storage: durable object copies that survive
node death.

Reference: python/ray/_private/external_storage.py — ExternalStorage ABC
(:72), spill_objects/restore_spilled_objects, and the smart_open-backed
cloud impl (:398).  TPU-native redesign: the local /dev/shm arena and the
node-local spill dir stay the fast tiers; this module adds a durable tier
keyed by URI scheme.  Spill uploads are registered in the GCS KV
(`spill_ext/<oid>`), so ANY node's agent can restore a dead node's
spilled object — the property the reference gets from S3-compatible
spill targets.

Backends (by URI scheme):
- ``file://`` — a filesystem root; pointed at a shared mount (NFS,
  gcsfuse) this is the production cloud tier on TPU pods, where every
  host mounts the same bucket.
- ``mock://`` — an in-tree fake remote store under a shared temp root,
  used by tests to prove cross-node restore without cloud creds (the
  reference tests against a local fake the same way).

Custom backends register with :func:`register_storage_scheme` (e.g. a
boto3/S3 impl where that dependency exists).
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Dict, Optional
from urllib.parse import urlparse


class ExternalStorage:
    """One durable copy per object, addressed by a URI."""

    def spill(self, oid_hex: str, data) -> str:
        """Write `data` (bytes-like) durably; return its URI."""
        raise NotImplementedError

    def restore(self, uri: str) -> Optional[bytes]:
        """Read a spilled copy back, or None if it's gone."""
        raise NotImplementedError

    def delete(self, uri: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """file:// rooted storage (shared mounts = durable across nodes).

    Writes are tmp-file + rename so a crashed writer never leaves a
    half-object a restorer could read (reference: external_storage.py
    writes whole spill files before registering them)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, oid_hex: str) -> str:
        # Two-level fanout keeps any one directory small at scale.
        return os.path.join(self.root, oid_hex[:2], oid_hex)

    def spill(self, oid_hex: str, data) -> str:
        path = self._path(oid_hex)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            raise
        return "file://" + path

    def restore(self, uri: str) -> Optional[bytes]:
        path = urlparse(uri).path
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, uri: str) -> None:
        try:
            os.unlink(urlparse(uri).path)
        except FileNotFoundError:
            pass


class MockCloudStorage(FileSystemStorage):
    """mock://bucket/prefix — a fake remote object store shared by every
    node on this machine (a temp-root namespace), for tests."""

    MOCK_ROOT = os.path.join(tempfile.gettempdir(), "ray_tpu_mock_cloud")

    def __init__(self, bucket_and_prefix: str):
        super().__init__(os.path.join(self.MOCK_ROOT, bucket_and_prefix))

    def spill(self, oid_hex: str, data) -> str:
        uri = super().spill(oid_hex, data)
        rel = os.path.relpath(urlparse(uri).path, self.MOCK_ROOT)
        return "mock://" + rel

    def restore(self, uri: str) -> Optional[bytes]:
        path = os.path.join(self.MOCK_ROOT, uri[len("mock://"):])
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, uri: str) -> None:
        path = os.path.join(self.MOCK_ROOT, uri[len("mock://"):])
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass


_SCHEMES: Dict[str, Callable[[str], ExternalStorage]] = {
    "file": lambda rest: FileSystemStorage(rest),
    "mock": lambda rest: MockCloudStorage(rest),
}


def register_storage_scheme(scheme: str,
                            factory: Callable[[str], ExternalStorage]):
    """Plug in a real cloud backend (s3://, gs://) where its client
    library exists; factory receives everything after ``scheme://``."""
    _SCHEMES[scheme] = factory


def storage_from_uri(uri: str) -> ExternalStorage:
    parsed = urlparse(uri)
    scheme = parsed.scheme or "file"
    if scheme not in _SCHEMES:
        raise ValueError(
            f"no external storage backend for scheme {scheme!r} "
            f"(have: {sorted(_SCHEMES)}; add one with "
            "register_storage_scheme)")
    rest = (parsed.netloc + parsed.path) if scheme != "file" else parsed.path
    return _SCHEMES[scheme](rest)
