"""Per-node agent: worker pool, lease scheduler, object-store host.

Equivalent of the reference's raylet (reference: src/ray/raylet/
node_manager.h:133, worker_pool.cc, local_lease_manager.cc) hosting the
shared-memory object store in-process (reference: main.cc:689
ObjectStoreRunner). Responsibilities:

- owns the node's /dev/shm arena lifecycle (create on start, unlink on exit)
- spawns and pools worker processes; leases them to submitters
  (reference: WorkerPool::PopWorker worker_pool.h:55, lease protocol in
  node_manager.proto:441 RequestWorkerLease)
- tracks node resources; placement-group bundle prepare/commit
  (reference: placement_group_resource_manager.cc, 2-phase commit)
- serves cross-node object pulls out of the local store and fetches remote
  objects into it (reference: object_manager/pull_manager.cc + push path)
- registers with the GCS and reports its resource view periodically
  (reference: ray_syncer)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from . import clocks, diagnosis, loopmon, protocol, rpc
from . import flight_recorder as frec
from .config import Config, get_config, set_config
from .ids import NodeID, WorkerID
from .shm_store import (ObjectExistsError, ShmStore, SpillTruncatedError,
                        StoreFullError)
from .. import exceptions as exc

logger = logging.getLogger("ray_tpu.agent")

IDLE_WORKER_KEEP = 8          # pooled idle workers kept hot per node
LEASE_IDLE_TIMEOUT_S = 2.0


def _needs_tpu(resources) -> bool:
    return any(k == "TPU" or k.startswith("TPU-") for k, v in
               (resources or {}).items() if v > 0)


def _write_file(path: str, data) -> None:
    with open(path, "wb") as f:
        f.write(data)


def _intervals_add(ivs: list, start: int, end: int) -> None:
    """Merge [start, end) into a sorted list of disjoint committed-byte
    intervals (in place).  Pulls commit chunks out of order, so the list
    stays short (≤ inflight window) in steady state."""
    import bisect
    i = bisect.bisect_left(ivs, (start, start))
    if i > 0 and ivs[i - 1][1] >= start:
        i -= 1
    j = i
    while j < len(ivs) and ivs[j][0] <= end:
        start = min(start, ivs[j][0])
        end = max(end, ivs[j][1])
        j += 1
    ivs[i:j] = [(start, end)]


def _intervals_cover(ivs: list, start: int, end: int) -> bool:
    """Whether [start, end) is fully inside one committed interval."""
    if start >= end:
        return True
    import bisect
    i = bisect.bisect_right(ivs, (start, float("inf"))) - 1
    return i >= 0 and ivs[i][0] <= start and ivs[i][1] >= end


def _read_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


class _ForkedProc:
    """Popen-shaped handle for a zygote-forked worker. The zygote (not
    the agent) is the child's parent and reaps it on SIGCHLD, so death is
    observed via /proc rather than waitpid; signals go by pid."""

    @staticmethod
    def _stat_fields(pid: int):
        """(state, starttime) from /proc/<pid>/stat; None if gone. comm may
        itself contain ')', so split on the LAST one."""
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                rest = f.read().rsplit(b")", 1)[1].split()
            return rest[0], rest[19]   # fields 3 and 22 (1-indexed)
        except (OSError, IndexError):
            return None

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: int | None = None
        st = self._stat_fields(pid)
        # starttime pins identity: a recycled pid after death+reap must
        # not make a dead worker look alive (or get SIGTERMed by proxy).
        self._starttime = st[1] if st else None

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        st = self._stat_fields(self.pid)
        if st is not None and st[0] != b"Z" and st[1] == self._starttime:
            return None
        self.returncode = 0
        return self.returncode

    def send_signal(self, sig):
        st = self._stat_fields(self.pid)
        if st is None or st[1] != self._starttime:
            return              # pid recycled: never signal a stranger
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def terminate(self):
        self.send_signal(signal.SIGTERM)

    def kill(self):
        self.send_signal(signal.SIGKILL)

    def wait(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("zygote-forked-worker",
                                                timeout)
            time.sleep(0.02)
        return self.returncode


class WorkerHandle:
    def __init__(self, worker_id: bytes, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.address = None           # set at registration
        self.conn: Optional[rpc.Connection] = None   # agent→worker
        self.registered = asyncio.Event()
        self.lease_id: Optional[bytes] = None
        self.lease_resources: Dict[str, float] = {}
        self.lease_bundle: Optional[Tuple[bytes, int]] = None  # PG bundle key
        self.needs_tpu = False        # pooled separately: TPU workers keep
        self.is_actor = False         # the accelerator client initialized
        self.has_env = False          # runtime-env workers never pool
        self.lease_owner_conn = None  # server conn that requested the lease
        self.actor_id: Optional[bytes] = None
        self.last_idle = time.monotonic()
        self.spawned_at = time.monotonic()
        # Diagnosis plane: grant stamp for the lease-stall detector
        # (granted-but-never-RUNNING); flagged-once latch per grant.
        self.lease_granted_at: Optional[float] = None
        self.lease_stall_flagged = False
        # Cluster epoch this lease was granted under (GCS HA fencing).
        self.granted_epoch = 0
        # Blocked-get CPU release (reference: NodeManager::
        # HandleNotifyDirectCallTaskBlocked, node_manager.cc — a worker
        # blocked in ray.get releases its CPU so queued work can run).
        self.blocked_depth = 0        # concurrent blocked gets in this worker
        self.blocked_cpus = 0.0       # CPU amount currently released


class NodeAgent:
    # Transfer counters are bumped from I/O shard threads too
    # (shard-local chunk serving): exact under the lock, which is
    # ns-scale against a multi-MiB chunk serve.  Class-level so
    # skeletal test instances share it.
    _served_lock = threading.Lock()

    def __init__(self, *, gcs_address, session_dir: str, node_id: bytes,
                 resources: Dict[str, float], labels: Dict[str, str],
                 store_capacity: int, host: str = "127.0.0.1"):
        self.gcs_address = tuple(gcs_address)
        self.session_dir = session_dir
        # Cluster epoch (GCS HA fencing token, docs/control_plane.md §8):
        # learned from registration + every heartbeat reply, monotonic.
        # Stamped into every lease grant; a lease request presenting an
        # OLDER epoch is rejected typed (REJECT_STALE_EPOCH) so owners
        # refresh and resubmit through the normal retry path.
        self.cluster_epoch = protocol.EPOCH_NONE
        from .runtime_env import UriCache
        self.uri_cache = UriCache(
            os.path.join(session_dir, "runtime_resources"))
        self.node_id = node_id
        self.host = host
        self.labels = labels
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.store_path = os.path.join(
            "/dev/shm", f"raytpu_{node_id.hex()[:12]}")
        self.store = ShmStore.create(self.store_path, store_capacity)
        self.workers: Dict[bytes, WorkerHandle] = {}
        self.idle_workers: List[WorkerHandle] = []      # CPU pool
        self.idle_tpu_workers: List[WorkerHandle] = []  # TPU pool
        self.leases: Dict[bytes, WorkerHandle] = {}
        self.bundles: Dict[Tuple[bytes, int], Dict[str, float]] = {}
        self.pinned: Dict[bytes, int] = {}   # object_id -> pin count (owner pins)
        # Spill manager state (reference: raylet LocalObjectManager,
        # local_object_manager.h:43 — spills pinned primaries to disk under
        # memory pressure, restores on demand).
        cfg = get_config()
        self.spilled: Dict[bytes, Tuple[str, int]] = {}  # oid -> (path, size)
        self._spilling: Set[bytes] = set()               # writes in flight
        self._disk_cached: Dict[bytes, int] = {}         # non-primary copies
        self._spill_dir = cfg.object_spill_dir or os.path.join(
            session_dir, "spill", node_id.hex()[:12])
        self._spill_threshold = cfg.object_spill_threshold
        # Durable external tier (reference: _private/external_storage.py):
        # spills also upload here + register in the GCS KV, so any node
        # can restore a dead node's spilled objects.
        self._ext = None
        self._ext_uris: Dict[bytes, str] = {}
        if cfg.object_spill_external_uri:
            from .external_storage import storage_from_uri
            self._ext = storage_from_uri(cfg.object_spill_external_uri)
        self._pull_inflight: Dict[bytes, asyncio.Future] = {}
        self._pull_waiters: List[Tuple[int, int, asyncio.Future]] = []  # heap
        self._pull_active = 0
        self._pull_seq = 0
        # --- Tiered-memory admission (the CreateRequestQueue analogue;
        # reference: plasma create_request_queue.h + SURVEY N15/N16's
        # unified object manager).  Creates that cannot reserve arena
        # headroom park in a bounded FIFO; _create_queue_loop retries the
        # HEAD as eviction/spill frees room (FIFO = no small-object
        # starvation of a big create), expiring entries typed.
        # _reserved is the admission ledger: oid -> (nbytes, expiry) for
        # creates granted headroom but not yet sealed — counted as
        # in-use by every sweep/admission decision, so a racing put can
        # never be granted the same headroom and a pressure sweep never
        # treats an unsealed in-progress region as reclaimable.
        from collections import deque as _cq
        self._create_queue: _cq = _cq()
        self._create_queue_depth_max = int(cfg.create_queue_depth)
        self._create_event = asyncio.Event()
        self._reserved: Dict[bytes, Tuple[int, float]] = {}
        self._pinned_floor = int(cfg.eviction_pinned_bytes_floor)
        # Spill/restore byte counters (observability catalog rows).
        self._spilled_bytes_total = 0
        self._restored_bytes_total = 0
        # Memory-pressure chaos (config mem_chaos): squeezes the
        # EFFECTIVE arena budget the admission/spill policy sees.
        # Consulted lazily via _capacity_scale() — no extra thread.
        self._mem_chaos = None
        if cfg.mem_chaos:
            from .chaos import MemChaos
            self._mem_chaos = MemChaos(cfg.mem_chaos)
        self._shed_threshold = float(cfg.lease_shed_pressure_threshold)
        self._leases_shed = 0
        # Replica-plane state (see docs/data_plane.md "replica directory"):
        # oid -> owner addr for SECONDARY copies this node registered with
        # an owner (pulled replicas; deregistered on eviction/free/drain so
        # directory entries can't outlive the bytes) ...
        self._replica_owner: Dict[bytes, tuple] = {}
        # ... oid -> owner addr for pinned PRIMARIES (pin_transfer/
        # pin_object stamp it; drain migration forwards it so the adoptive
        # node can repoint the owner's directory) ...
        self._pinned_owner: Dict[bytes, tuple] = {}
        # ... and in-progress arena pulls serving their already-committed
        # chunks to peers (receiver-becomes-source, Cornet-style):
        # oid -> {"size", "buf" (create_buffer view or None for
        # disk-destined pulls), "done" (committed [start, end) intervals)}.
        self._partial: Dict[bytes, dict] = {}
        # Transfer counters (heartbeat -> GCS node view -> `ray_tpu list
        # nodes` / dashboard transfer column).
        self._bytes_served = 0
        self._bytes_pulled = 0
        self._last_pull_sources = 0   # observability: swarm width of the
        #                               most recent pull on this node
        self._chunk_bytes = cfg.object_transfer_chunk_bytes
        self._max_pulls = cfg.max_concurrent_pulls
        self._max_inflight_chunks = cfg.object_transfer_max_inflight_chunks
        self._chunk_timeout = cfg.object_transfer_chunk_timeout_s
        # Per-peer link health: addr -> {"lat": deque[s], "rtt": EMA s,
        # "rate": EMA B/s, "fail": count, "ts": monotonic of last sample}.
        # Fed by every fetch_chunk/object_info round trip; drives the
        # hedge delay (p95 of recent latencies) and rides heartbeats to
        # the GCS as gray-failure evidence about OTHER nodes.
        self._peer_stats: Dict[tuple, dict] = {}
        # Hedge budget (The Tail at Scale): hedged fetches are capped at
        # a fraction of total fetches plus a small burst, so hedging
        # can't amplify load on a cluster that is slow because it is
        # OVERLOADED rather than gray.
        self._hedge_enabled = cfg.pull_hedge_enabled
        self._hedge_delay_ms = cfg.pull_hedge_delay_ms
        self._hedge_budget_frac = cfg.pull_hedge_budget_fraction
        self._hedge_total = 0
        self._hedge_used = 0
        # Flight-recorder rows whose flush notify failed, kept for the
        # next heartbeat tick (bounded at ring capacity; overflow folds
        # into the recorder's drop counter — no silent loss).
        self._frec_retry: List[dict] = []
        # Drop-accounting reporter key: PROCESS-stable, deliberately not
        # node_id — a fresh-id rejoin (_rejoin_with_fresh_id) would
        # otherwise re-report the same cumulative drop count under a new
        # key and the GCS would double-count it.
        self._telemetry_src = os.urandom(8)
        # Parked lease requests: (params, conn, reply_future, deadline),
        # FIFO-granted by _parked_lease_loop as resources free (reference:
        # ClusterLeaseManager's lease queue).
        from collections import deque as _dq
        self._parked_leases: _dq = _dq()
        self._park_event = asyncio.Event()
        # Daemon I/O sharding (config daemon_io_shards): accepted
        # connections live on shard event-loop threads.  Shard-local
        # handlers are the pure arena/io ones — `ping` and the sealed-
        # object `fetch_chunk` fast path (the shm store is cross-process
        # shared memory, so cross-thread reads are its normal operating
        # mode); every state-touching branch FAST_FALLBACKs into the
        # batched main-loop hop.
        self._io_shards = rpc.make_io_shard_pool("agent")
        # Compiled-DAG channel plane: rings created here on behalf of
        # remote compilers + bridge threads pumping cross-node edges
        # (see _private/dag_channels.py and docs/dag.md).
        from .dag_channels import DagChannelManager
        self._dag_chans = DagChannelManager(self.store)
        self._server = rpc.RpcServer(
            self._handlers(), name="agent",
            on_client_close=self._on_client_close,
            io_shards=self._io_shards,
            shard_handlers={
                # The SAME callable as the main handlers dict: the
                # t1/t2 stamp semantics feed clock-offset estimation
                # and must never diverge between modes.
                "ping": self._h_ping,
                "fetch_chunk": self._sh_fetch_chunk,
                # CHAOS (diagnosis_chaos_enabled only): wedge the loop
                # that serves this conn on purpose — the loop-wedge
                # detector's fault-injection hook.  Registered on the
                # shard plane so a sharded agent stalls a SHARD thread.
                **({"debug_stall_loop": self._sh_debug_stall}
                   if cfg.diagnosis_chaos_enabled else {}),
            })
        self.gcs: Optional[rpc.Connection] = None
        self._spawn_lock = asyncio.Lock()
        self._peer_conns: Dict[tuple, rpc.Connection] = {}
        self._tasks: List[asyncio.Task] = []
        self._shutdown = False
        # Graceful drain state (reference: raylet drain / autoscaler
        # DrainNode): reason string while draining — new leases/actors/
        # bundles are refused (with spillback), in-flight leases finish,
        # pinned primaries migrate to a peer before the node exits.
        # _drain_deadline bounds the state itself: well past it, a drain
        # whose orchestrator vanished (GCS crash mid-drain) is abandoned
        # rather than leaving a permanent zombie (see _report_loop).
        self._draining: Optional[str] = None
        self._drain_deadline: float = 0.0
        # Primaries this node adopted from a draining peer (oid set): a
        # later owner free must also clear the cluster-wide "migrated"
        # KV record the drain left behind.
        self._adopted: Set[bytes] = set()
        # oid -> destination agent address for primaries migrated OFF this
        # node while it drains: frees arriving here before teardown are
        # forwarded so the adopted copy (and its pin) can't leak.
        self._migrated_away: Dict[bytes, tuple] = {}
        # worker_id -> {"reason", "ts"}: deaths caused by the OOM monitor,
        # queried by owners via h_worker_fate for typed errors.
        self._oom_kills: Dict[bytes, dict] = {}
        # Optional kernel-level worker isolation (reference: cgroup2
        # system/application split; config `cgroup_enabled`).
        self._worker_cgroup = None
        if cfg.cgroup_enabled:
            from .cgroup import WorkerCgroup
            mem = cfg.cgroup_memory_max_bytes or None
            grp = WorkerCgroup(f"ray_tpu_{self.node_id.hex()[:8]}",
                               memory_max=mem)
            self._worker_cgroup = grp if grp.active else None

    def _handlers(self):
        return {
            "register_worker": self.h_register_worker,
            "request_lease": self.h_request_lease,
            "return_lease": self.h_return_lease,
            "create_actor_worker": self.h_create_actor_worker,
            "actor_worker_died": self.h_actor_worker_died,
            "prepare_bundle": self.h_prepare_bundle,
            "reserve_bundles": self.h_reserve_bundles,
            "commit_bundle": self.h_commit_bundle,
            "return_bundle": self.h_return_bundle,
            "drain": self.h_drain,
            "adopt_primary": self.h_adopt_primary,
            "pin_object": self.h_pin_object,
            "pin_transfer": self.h_pin_transfer,
            "unpin_object": self.h_unpin_object,
            "free_objects": self.h_free_objects,
            "fetch_from_store": self.h_fetch_from_store,
            "object_info": self.h_object_info,
            "fetch_chunk": self.h_fetch_chunk,
            "pull_object": self.h_pull_object,
            "ensure_space": self.h_ensure_space,
            "reserve_create": self.h_reserve_create,
            "spill_path": self.h_spill_path,
            "spill_register": self.h_spill_register,
            "restore_object": self.h_restore_object,
            "node_info": self.h_node_info,
            "store_stats": self.h_store_stats,
            "list_objects": self.h_list_objects,
            # Timestamped ping: the GCS health probe doubles as the
            # clock-alignment probe (NTP t1/t2 server stamps; clocks.wall
            # so injected chaos skew is visible to the estimator exactly
            # like a genuinely off host clock).  Value is ignored by
            # plain liveness callers.  Shared with shard_handlers —
            # sharded and single-loop mode must stamp identically.
            "ping": self._h_ping,
            "worker_fate": self.h_worker_fate,
            "worker_blocked": self.h_worker_blocked,
            "worker_unblocked": self.h_worker_unblocked,
            "profile_worker": self.h_profile_worker,
            "node_profile": self.h_node_profile,
            # Agent's OWN stacks/cpu_profile (diagnosis plane): the
            # cluster_profile fan-out reaches daemons through these.
            **diagnosis.profile_handlers("agent"),
            **({"debug_stall_loop": self._sh_debug_stall}
               if get_config().diagnosis_chaos_enabled else {}),
            "list_logs": self.h_list_logs,
            "read_log": self.h_read_log,
            "shutdown": self.h_shutdown,
            **self._dag_chans.handlers(),
        }

    @staticmethod
    def _h_ping(conn, p):
        return {"pong": True, "t1": clocks.wall(), "t2": clocks.wall()}

    def _sh_debug_stall(self, conn, p):
        """CHAOS (diagnosis_chaos_enabled): block THIS handler's loop
        thread with a synchronous sleep — a real wedge, not a
        simulation: the loopmon probe stops ticking, the stale gauge
        grows, and the watchdog must catch it from its sibling thread."""
        time.sleep(min(float(p.get("seconds", 2.0)), 30.0))
        return True

    # ------------------------------------------------------------ lifecycle --
    async def start(self) -> tuple:
        addr = await self._server.start_tcp(self.host, 0)
        self.address = addr
        # Busy-fraction probe for the main loop (I/O shards install
        # their own under shard<i>): exported per node so single-core
        # daemon saturation is a gauge, not an inference.
        loopmon.install("main")
        cfg = get_config()
        if cfg.diagnosis_enabled:
            self._loop = asyncio.get_running_loop()
            self._watchdog = diagnosis.Watchdog(
                daemon_name="agent", node_id=self.node_id.hex(),
                detectors=[diagnosis.loop_wedge_detector()],
                notify=self._anomaly_from_thread,
                poll_s=cfg.diagnosis_poll_ms / 1000.0)
            self._watchdog.start()

        self.gcs = rpc.ReconnectingConnection(
            self.gcs_address, name="agent->gcs",
            handlers={"pubsub": self._on_pubsub},
            on_reconnect=self._register_gcs,
            # Every reconnect attempt re-reads the advertised address:
            # after a GCS failover the promoted standby serves on a new
            # port, and re-homing rides this same jittered dial loop.
            resolver=lambda: protocol.resolve_gcs_address(
                self.session_dir, fallback=self.gcs_address))
        await self.gcs.ensure()
        self._tasks.append(asyncio.ensure_future(self._report_loop()))
        self._tasks.append(asyncio.ensure_future(self._parked_lease_loop()))
        self._tasks.append(asyncio.ensure_future(self._create_queue_loop()))
        self._tasks.append(asyncio.ensure_future(self._reap_loop()))
        if get_config().worker_fork_server:
            # Warm the fork-server immediately: its one-time heavy import
            # runs while the node finishes bootstrapping.
            self._ensure_zygote()
        self._tasks.append(asyncio.ensure_future(self._prestart_workers()))
        self._tasks.append(asyncio.ensure_future(self._memory_monitor_loop()))
        logger.info("agent %s on %s, store %s",
                    self.node_id.hex()[:8], addr, self.store_path)
        return addr

    async def _prestart_workers(self):
        """Warm the idle pool so first leases skip process startup
        (reference: worker_pool.cc PrestartWorkers)."""
        n = int(get_config().worker_prestart_count)
        for _ in range(max(0, n)):
            if self._shutdown:
                return
            try:
                wh = await self._pop_worker(None)
            except rpc.RpcError:
                return
            wh.last_idle = time.monotonic()
            self.idle_workers.append(wh)

    async def _register_gcs(self, conn):
        """Registration, run on every (re)connect: a restarted GCS replays
        its journal with nodes marked not-alive; re-registering brings
        this node back (reference: raylet re-registration after
        RayletNotifyGCSRestart, core_worker.proto:467).  Reads self.node_id
        at call time so a fresh-id rejoin reuses it unchanged."""
        reply = await conn.call("register_node", {
            "node_id": self.node_id,
            "address": list(self.address),
            "resources": self.resources_total,
            "labels": self.labels,
            "store_path": self.store_path,
            "session_dir": self.session_dir,
            # The agent never reads the cluster view from the reply;
            # skipping it keeps a mass (re-)registration wave O(N) on
            # the GCS instead of O(N^2) view-building.
            "view": False,
        })
        if isinstance(reply, dict):
            self._learn_epoch(reply.get(protocol.EPOCH_KEY))

    def _learn_epoch(self, epoch):
        """Adopt a (monotonically higher) cluster epoch from a GCS reply.
        A bump mid-flight means a standby took over; grants this agent
        mints from now on carry the new epoch, and requests still
        presenting the old one get the typed stale-epoch rejection."""
        if not isinstance(epoch, int) or epoch <= self.cluster_epoch:
            return
        if self.cluster_epoch != protocol.EPOCH_NONE:
            logger.warning(
                "cluster epoch bumped %d -> %d (GCS failover observed); "
                "new lease grants are fenced to the new epoch",
                self.cluster_epoch, epoch)
        self.cluster_epoch = epoch

    async def _rejoin_with_fresh_id(self):
        """The GCS rejected our heartbeat: this node was marked dead while
        the agent was actually alive (health-check false positive — e.g. a
        GC pause outlived the failure budget).  Death is permanent for
        consumers (actors restarted elsewhere, primaries written off), so
        zombieing on the old id helps nobody: rejoin as a FRESH node id —
        same agent process, same store, new identity (reference: a
        restarted raylet likewise registers a new node id)."""
        from .ids import NodeID
        old = self.node_id
        self.node_id = NodeID.from_random().binary()
        logger.warning(
            "GCS rejected heartbeats for node %s (marked dead); "
            "re-registering as fresh node %s",
            old.hex()[:8], self.node_id.hex()[:8])
        await self._register_gcs(self.gcs)

    async def _report_loop(self):
        cfg = get_config()
        period = cfg.resource_report_period_ms / 1000.0
        # Phase desync (like rpc._backoff_delay's jitter): seed this
        # agent's tick with a pid-derived phase offset so N agents
        # started (or healed) together spread their heartbeat+telemetry
        # bursts across the period instead of stampeding the GCS on the
        # same tick — at fleet size the synchronized burst is visible as
        # p99 spikes on everything the GCS serves.
        await asyncio.sleep(period * rpc._jitter_rng.random())
        while not self._shutdown:
            await asyncio.sleep(period)
            try:
                self._sweep_replica_registrations()
                if self.gcs and not self.gcs.closed:
                    ok = await self.gcs.call("report_resources", {
                        "node_id": self.node_id,
                        "available": self.resources_available,
                        # Gray-failure evidence about peers: per-peer
                        # RTT/rate EMAs from this node's transfer paths
                        # (the GCS folds them into suspicion scores).
                        "peer_stats": self._peer_stats_snapshot(),
                        # Data-plane counters for the node views
                        # (`ray_tpu list nodes` / dashboard transfer
                        # column).
                        "transfer": {"bytes_served": self._bytes_served,
                                     "bytes_pulled": self._bytes_pulled},
                        # Runtime gauges for the node view (CLI summary /
                        # dashboard node table); the metrics flush below
                        # exports the same numbers as node-labeled
                        # series.
                        "runtime": self._runtime_stats(),
                    })
                    # Flight-recorder + metrics flush rides THIS tick —
                    # the batching discipline: no new per-event RPCs,
                    # one notify per heartbeat when there is anything
                    # to ship.
                    self._flush_telemetry()
                    if isinstance(ok, dict):
                        self._learn_epoch(ok.get(protocol.EPOCH_KEY))
                    if ok is False and not self._shutdown \
                            and self._draining is None:
                        # Rejected = we're listed dead.  (Never during a
                        # drain: its mark-dead is intentional and the
                        # shutdown notify is on the way.)
                        await self._rejoin_with_fresh_id()
                    elif self._draining is not None and not self._shutdown \
                            and time.monotonic() > \
                            self._drain_deadline + 30.0:
                        # Well past the drain deadline with no teardown:
                        # the orchestrator is gone (GCS crash mid-drain).
                        if ok is False:
                            # The drain DID conclude (we're dead at the
                            # GCS) but the shutdown notify was lost — exit
                            # as it would have made us.
                            logger.warning(
                                "drain concluded but teardown notify lost; "
                                "exiting")
                            await self.h_shutdown(None, {"graceful": True})
                        else:
                            # Still alive at the GCS: the drain was
                            # abandoned — return to service instead of
                            # zombieing (refusing leases forever).
                            logger.warning(
                                "drain (%s) abandoned past deadline; "
                                "returning node to service", self._draining)
                            self._draining = None
                            self._drain_deadline = 0.0
                            self._kick_parked()
            except Exception:
                # One slow/failed report (GCS busy, reconnecting, ...) must
                # never kill the loop: a dead report loop freezes this
                # node's resource view at the GCS and starves scheduling.
                pass

    # ----------------------------------------------------- telemetry -------
    def _runtime_stats(self) -> Dict[str, float]:
        """Small gauge set riding the heartbeat into the node view."""
        try:
            st = self.store.stats()
        except Exception:
            st = {}
        lm = loopmon.snapshot()
        shard_busy = [v for k, v in lm.items() if k.startswith("shard")]
        # Heartbeat tick doubles as the arena source of the shared
        # pressure signal (memory_monitor.pressure_signal): lease
        # shedding and KV demotion drain the same number the create
        # queue backpressures on.
        pressure = self._arena_pressure(st)
        try:
            from .memory_monitor import pressure_signal
            pressure_signal().report("arena", pressure)
            if self._mem_chaos is not None:
                self._mem_chaos.report_pressure()
        except Exception:
            pass
        return {
            "lease_queue_depth": float(len(self._parked_leases)),
            "active_leases": float(len(self.leases)),
            "num_workers": float(len(self.workers)),
            "arena_used_bytes": float(st.get("bytes_in_use", 0)),
            "arena_capacity_bytes": float(st.get("capacity", 0)),
            "arena_pressure": pressure,
            "create_queue_depth": float(len(self._create_queue)),
            # Loop saturation for `ray_tpu summary`'s busy column:
            # main-loop busy fraction / max across I/O shards.
            "loop_busy": float(lm.get("main", 0.0)),
            "loop_busy_shard_max": float(max(shard_busy)
                                         if shard_busy else 0.0),
            "io_shards": float(len(self._io_shards)
                               if self._io_shards else 0),
        }

    def _flush_telemetry(self) -> None:
        """Ship buffered flight-recorder rows + this daemon's metric
        snapshot to the GCS sinks.  Fire-and-forget notifies on the
        existing GCS connection, sent at heartbeat rate — the recorder
        ring absorbs bursts between ticks and counts what it sheds.
        A failed notify keeps the drained rows for the next tick
        (bounded; overflow is COUNTED via note_lost, never silent)."""
        if self.gcs is None or self.gcs.closed:
            return
        rec = frec.recorder()
        rows = self._frec_retry + rec.drain(node_id=self.node_id)
        self._frec_retry = []
        if rows:
            try:
                self.gcs.notify("task_events", {
                    "blob": rpc._pack(rows), "n": len(rows),
                    "src": self._telemetry_src, "dropped": rec.dropped})
            except rpc.RpcError:
                keep = rows[-rec.capacity:]
                rec.note_lost(len(rows) - len(keep))
                self._frec_retry = keep
        if not get_config().metrics_export_enabled:
            return
        try:
            self.gcs.notify("report_metrics", {
                "worker_id": self.node_id,
                "node_id": self.node_id,
                "metrics": self._metrics_snapshot()})
        except rpc.RpcError:
            pass

    def _metrics_snapshot(self) -> List[dict]:
        """This daemon's registry + runtime gauges as metric rows
        (unified export: the same shape util.metrics snapshots use, so
        the GCS merges user and runtime series through one sink)."""
        from ..util import metrics as _metrics
        now = time.time()
        # node_id is stamped at the SOURCE (not injected at the GCS) so
        # runtime series are per-node while user metrics keep whatever
        # label set their authors chose (a silently injected label
        # would change user series identity).
        lab = {"daemon": "agent", "node_id": self.node_id.hex()}

        def row(name, value, typ="gauge", help_="", labels=None):
            return {"name": name, "type": typ, "help": help_, "ts": now,
                    "labels": labels or lab, "value": float(value)}

        rt = self._runtime_stats()
        out = [
            row("ray_tpu_arena_used_bytes", rt["arena_used_bytes"],
                help_="shm arena bytes in use"),
            row("ray_tpu_arena_capacity_bytes",
                rt["arena_capacity_bytes"]),
            row("ray_tpu_lease_queue_depth", rt["lease_queue_depth"],
                help_="parked (queued) lease requests"),
            row("ray_tpu_active_leases", rt["active_leases"]),
            row("ray_tpu_node_workers", rt["num_workers"]),
            row("ray_tpu_transfer_served_bytes_total",
                self._bytes_served, "counter"),
            row("ray_tpu_transfer_pulled_bytes_total",
                self._bytes_pulled, "counter"),
            row("ray_tpu_arena_pressure", rt["arena_pressure"],
                help_="arena occupancy incl. unsealed create "
                      "reservations, over the EFFECTIVE capacity "
                      "(mem_chaos squeezes shrink the denominator)"),
            row("ray_tpu_create_queue_depth", rt["create_queue_depth"],
                help_="creates parked in the FIFO admission queue "
                      "waiting for eviction/spill headroom"),
            row("ray_tpu_spilled_bytes_total",
                self._spilled_bytes_total, "counter",
                help_="bytes written to the NVMe/external spill tier"),
            row("ray_tpu_restored_bytes_total",
                self._restored_bytes_total, "counter",
                help_="bytes restored from spill files into the arena"),
        ]
        # Per-loop busy fractions: main + every I/O shard, node-labeled
        # (the gcs exports its own under daemon="gcs").  Stale entries
        # stay in the export with their probe age alongside — a wedged
        # loop must ALARM in the gauges, not vanish from them.
        for label, info in loopmon.snapshot_full().items():
            out.append(row("ray_tpu_daemon_loop_busy_ratio", info["ratio"],
                           labels={**lab, "loop": label},
                           help_="CPU-seconds per wall-second burned by "
                                 "the thread running this event loop"))
            out.append(row("ray_tpu_daemon_loop_stale_seconds",
                           info["stale_s"],
                           labels={**lab, "loop": label},
                           help_="age of this loop's last busy probe "
                                 "tick; grows past the ~0.5s period "
                                 "when the loop stops servicing "
                                 "callbacks (wedged or stopped)"))
        sst = self._server.shard_stats()
        if sst["shards"]:
            out.append(row("ray_tpu_daemon_io_shard_hops_total",
                           sst["hops"], "counter"))
            out.append(row("ray_tpu_daemon_io_shard_requests_total",
                           sst["submitted"], "counter"))
        # Common per-process rows (io_stats, copy audit, recorder
        # counters): shared with the core worker's export so the two
        # cannot diverge.
        out.extend(frec.export_rows(lab))
        # User/util metrics registered inside this process ride along.
        out.extend(_metrics.registry_snapshot())
        return out

    async def _reap_loop(self):
        """Detect dead worker processes, release their leases, tell GCS about
        dead actors (reference: worker failure path, gcs_service.proto:388
        ReportWorkerFailure)."""
        while not self._shutdown:
            await asyncio.sleep(0.5)
            for wid, wh in list(self.workers.items()):
                if wh.proc.poll() is not None:
                    try:
                        await self._on_worker_death(wh)
                    except Exception:
                        logger.exception(
                            "worker death handling failed; lease state may "
                            "need the next reap pass")
            # Sweep leases whose owner connection is closed: the
            # disconnect callback covers the common case, but a grant
            # that registers in the same loop tick the teardown runs (or
            # any future ordering hole) must not leak CPUs forever —
            # the raylet likewise returns leases on client disconnect
            # unconditionally (reference: node_manager.cc disconnect
            # path).
            for lease_id, wh in list(self.leases.items()):
                conn = wh.lease_owner_conn
                if conn is not None and conn.closed:
                    logger.warning(
                        "sweeping lease %s from disconnected client",
                        lease_id.hex()[:8])
                    try:
                        self._reclaim_lease(lease_id, wh)
                    except Exception:
                        # The reap loop must survive everything: a dead
                        # loop means no death detection node-wide.
                        logger.exception("lease sweep failed for %s",
                                         lease_id.hex()[:8])
            try:
                await self._check_lease_stalls()
            except Exception:
                logger.exception("lease-stall detector pass failed")

    async def _check_lease_stalls(self):
        """Diagnosis-plane detector: a lease granted long ago whose
        worker has started ZERO tasks since the grant (and runs none
        now) — the owner wedged before pushing, or the push vanished.
        Probed via the worker's exec_stats (AGES, not timestamps:
        monotonic clocks don't compare across processes); flagged once
        per grant."""
        cfg = get_config()
        if not cfg.diagnosis_enabled:
            return
        stall_s = cfg.diagnosis_lease_stall_s
        now = time.monotonic()
        for lease_id, wh in list(self.leases.items()):
            if (wh.lease_granted_at is None or wh.lease_stall_flagged
                    or wh.lease_id is None):
                continue
            age = now - wh.lease_granted_at
            if age < stall_s:
                continue
            if wh.conn is None or wh.conn.closed \
                    or wh.proc.poll() is not None:
                continue        # dead-worker path handles these
            try:
                st = await wh.conn.call("exec_stats", {}, timeout=5)
            except rpc.RpcError:
                continue
            if wh.lease_id is None or wh.lease_stall_flagged:
                continue        # released/flagged while we awaited
            started_age = st.get("last_task_started_age_s")
            never_ran = started_age is None or started_age > age
            if st.get("running") or not never_ran:
                continue
            wh.lease_stall_flagged = True
            diagnosis.record_anomaly(
                "lease_stalled", daemon="agent",
                node_id=self.node_id.hex(), notify=self._send_anomaly,
                lease_id=lease_id.hex(), worker_id=wh.worker_id.hex(),
                lease_age_s=round(age, 3))

    async def _memory_monitor_loop(self):
        """Kill-by-policy when node memory crosses the threshold
        (reference: raylet MemoryMonitor + GroupByOwnerIdWorkerKillingPolicy,
        node_manager.cc:229-230)."""
        from .config import get_config
        from .memory_monitor import (GroupByOwnerPolicy, kill_worker,
                                     node_memory_usage, pressure_signal)
        cfg = get_config()
        period = cfg.memory_monitor_refresh_ms / 1000.0
        threshold = cfg.memory_usage_threshold
        if period <= 0 or threshold >= 1.0:
            return
        policy = GroupByOwnerPolicy()
        sig = pressure_signal()
        while not self._shutdown:
            await asyncio.sleep(period)
            try:
                used, total = node_memory_usage()
                frac = used / max(total, 1)
                # Node RAM feeds the SAME pressure signal the create
                # queue and lease shedding drain — but only past the OOM
                # threshold: ordinary host occupancy (a busy dev box)
                # must not flip the cluster into shed mode.
                if frac > threshold:
                    sig.report("node", frac)
                else:
                    sig.clear("node")
                if frac <= threshold:
                    continue
                victim = policy.pick(list(self.workers.values()))
                if victim is None:
                    continue
                reason = (
                    f"node memory usage {frac:.1%} above threshold "
                    f"{threshold:.1%}; killed worker pid={victim.proc.pid} "
                    f"(group-by-owner policy)")
                logger.warning("OOM monitor: %s", reason)
                # Prune stale fate records (owners query within seconds of
                # the crash; 10 min is a generous triage window).
                cutoff = time.monotonic() - 600.0
                for wid in [w for w, i in self._oom_kills.items()
                            if i["ts"] < cutoff]:
                    del self._oom_kills[wid]
                self._oom_kills[victim.worker_id] = {
                    "reason": reason, "ts": time.monotonic()}
                kill_worker(victim, reason)
                # Let the kill land + the reaper release resources before
                # re-evaluating, so one spike doesn't massacre the pool.
                await asyncio.sleep(max(period, 1.0))
            except Exception:
                logger.exception("memory monitor pass failed")

    async def h_worker_fate(self, conn, p):
        """Owner-side crash triage: was this worker OOM-killed?
        (reference: the raylet annotates worker death with
        OOM-kill details so owners raise OutOfMemoryError)."""
        info = self._oom_kills.get(p["worker_id"])
        return {"oom_killed": info is not None,
                "reason": (info or {}).get("reason", "")}

    async def _on_worker_death(self, wh: WorkerHandle):
        self.workers.pop(wh.worker_id, None)
        if wh in self.idle_workers:
            self.idle_workers.remove(wh)
        if wh in self.idle_tpu_workers:
            self.idle_tpu_workers.remove(wh)
        if wh.lease_id is not None:
            self._release_resources(self._settle_lease_release(wh),
                                    wh.lease_bundle)
            self.leases.pop(wh.lease_id, None)
        logger.warning("worker %s (pid %s) died", wh.worker_id.hex()[:8],
                       wh.proc.pid)
        if wh.is_actor and wh.actor_id and self.gcs and not self.gcs.closed:
            # Report actor death so the GCS can restart-or-bury (reference:
            # ReportWorkerFailure → GcsActorManager::OnWorkerDead).
            oom = self._oom_kills.get(wh.worker_id)
            try:
                await self.gcs.call("actor_failed", {
                    "actor_id": wh.actor_id,
                    "reason": (oom["reason"] if oom else
                               f"worker process {wh.proc.pid} exited with "
                               f"code {wh.proc.returncode}")})
            except (rpc.RpcError, asyncio.TimeoutError):
                pass

    def _on_pubsub(self, conn, p):
        pass  # agents currently only publish

    async def close(self):
        self._shutdown = True
        self._dag_chans.stop_all()
        for t in self._tasks:
            t.cancel()
        z = getattr(self, "_zygote", None)
        if z is not None and z.poll() is None:
            try:
                z.stdin.close()          # zygote exits on EOF
                z.terminate()
            except OSError:
                pass
        for wh in list(self.workers.values()):
            try:
                wh.proc.terminate()
            except ProcessLookupError:
                pass
        await self._server.close()
        if self._io_shards is not None:
            # After the server: bridged connection closes need the
            # shard loops alive to run.
            self._io_shards.close()
        self.store.close()
        if self._worker_cgroup is not None:
            # rmdir on a cgroup with live members returns EBUSY: give the
            # terminated workers a moment to exit before removing.
            for _ in range(30):
                if all(wh.proc.poll() is not None
                       for wh in self.workers.values()):
                    break
                await asyncio.sleep(0.1)
            for wh in self.workers.values():
                if wh.proc.poll() is None:
                    wh.proc.kill()
                    try:
                        wh.proc.wait(timeout=2)
                    except Exception:
                        pass
            self._worker_cgroup.close()
        try:
            os.unlink(self.store_path)
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------- workers --
    def _zygote_env(self) -> Dict[str, str]:
        """Env for the fork-server: identical to a default CPU worker's
        (sitecustomize stripped, CPU-only jax) so forked children need no
        import-time env fixups."""
        from .node import child_env
        env = child_env(None)
        strip = get_config().worker_pythonpath_strip_cpu
        if strip:
            parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p and strip not in p]
            env["PYTHONPATH"] = os.pathsep.join(parts)
        if env.get("JAX_PLATFORMS", "") not in ("", "cpu"):
            env["JAX_PLATFORMS"] = "cpu"
        return env

    def _ensure_zygote(self) -> Optional[subprocess.Popen]:
        z = getattr(self, "_zygote", None)
        if z is not None and z.poll() is None:
            return z
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        errf = open(os.path.join(log_dir, "zygote.err"), "ab")
        try:
            self._zygote = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.zygote"],
                env=self._zygote_env(), stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, stderr=errf,
                cwd=os.getcwd(), start_new_session=True)
        except OSError:
            self._zygote = None
        return self._zygote

    def _zygote_fork(self, req: dict) -> int:
        """Blocking fork request (call via run_in_executor under
        _spawn_lock — the pipe protocol is strictly serial)."""
        z = self._zygote
        z.stdin.write(json.dumps(req).encode() + b"\n")
        z.stdin.flush()
        line = z.stdout.readline()
        if not line:
            raise rpc.RpcError("worker fork-server died")
        return json.loads(line)["pid"]

    async def _spawn_worker(self, env_extra: Dict[str, str] | None = None,
                            needs_tpu: bool = False,
                            cwd: str | None = None) -> WorkerHandle:
        worker_id = WorkerID.from_random().binary()
        from .node import child_env
        env = child_env(env_extra)
        if not needs_tpu:
            # Strip accelerator site hooks (e.g. a sitecustomize that eagerly
            # initializes the TPU client): CPU workers start in ~20ms instead
            # of seconds and never touch chip state (reference analogue:
            # workers outside TPU leases get no TPU_VISIBLE_CHIPS).
            strip = get_config().worker_pythonpath_strip_cpu
            if strip:
                parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                         if p and strip not in p]
                env["PYTHONPATH"] = os.pathsep.join(parts)
            # A worker with no TPU lease must never initialize the chip —
            # one client per chip (reference analogue: no TPU_VISIBLE_CHIPS
            # → no accelerator; jax_trainer.py:92-94 driver warning).
            if env.get("JAX_PLATFORMS", "") not in ("", "cpu"):
                env["JAX_PLATFORMS"] = "cpu"
        chaos_spec = get_config().rpc_chaos
        if chaos_spec:
            # Chaos must reach worker processes too (their config builds
            # from env; _system_config stops at the daemons' argv).
            env.setdefault("RAY_TPU_rpc_chaos", chaos_spec)
        link_spec = get_config().link_chaos
        if link_spec:
            # Slow-NODE mode: a node whose agent is link-degraded
            # degrades its workers the same way (the whole host shares
            # the gray NIC).
            env.setdefault("RAY_TPU_link_chaos", link_spec)
        skew = get_config().clock_skew_s
        if skew:
            # Skewed-NODE mode: processes on one host share the system
            # clock, so an injected skew must reach the workers too or
            # the node's own telemetry would disagree with itself.
            env.setdefault("RAY_TPU_clock_skew_s", str(skew))
        # The task-hung watchdog runs IN the worker: its thresholds must
        # reach worker processes too (their config builds from env;
        # _system_config stops at the daemons' argv).
        dcfg = get_config()
        for _k in ("diagnosis_enabled", "diagnosis_poll_ms",
                   "diagnosis_task_hang_multiple",
                   "diagnosis_task_hang_min_s",
                   "diagnosis_task_hang_default_s",
                   "diagnosis_serving_silence_s"):
            env.setdefault(f"RAY_TPU_{_k}", str(getattr(dcfg, _k)))
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_AGENT_ADDR"] = json.dumps(list(self.address))
        env["RAY_TPU_GCS_ADDR"] = json.dumps(list(self.gcs_address))
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_STORE_PATH"] = self.store_path
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.out")
        err_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.err")
        proc = None
        if (not needs_tpu and env_extra is None and cwd is None
                and get_config().worker_fork_server):
            # Default-env CPU worker: fork from the warm zygote (~100ms)
            # instead of exec+reimport (~seconds on small hosts).
            z = self._ensure_zygote()
            if z is not None:
                req = {"env": {k: env[k] for k in env
                               if k.startswith(("RAY_TPU_", "JAX_"))},
                       "cwd": os.getcwd(),
                       "stdout": out_path, "stderr": err_path}
                loop = asyncio.get_running_loop()
                try:
                    async with self._spawn_lock:
                        pid = await loop.run_in_executor(
                            None, self._zygote_fork, req)
                    proc = _ForkedProc(pid)
                except (rpc.RpcError, OSError, ValueError):
                    proc = None          # zygote broken: exec fallback
        if proc is None:
            out = open(out_path, "ab")
            err = open(err_path, "ab")
            # Interpreter override (conda runtime env) / container launch
            # (container runtime env) — both set by UriCache.setup.
            py = env.pop("RAY_TPU_WORKER_PYTHON", None) or sys.executable
            cmd = [py, "-m", "ray_tpu._private.worker_main"]
            container = env.pop("RAY_TPU_WORKER_CONTAINER", None)
            if container:
                cmd = self._container_cmd(json.loads(container), env,
                                          cwd or os.getcwd(),
                                          set(env_extra or ()))
            proc = subprocess.Popen(
                cmd, env=env, stdout=out, stderr=err,
                cwd=cwd or os.getcwd(), start_new_session=True)
        if self._worker_cgroup is not None:
            self._worker_cgroup.add(proc.pid)
        wh = WorkerHandle(worker_id, proc)
        wh.needs_tpu = needs_tpu
        wh.has_env = bool(env_extra) or cwd is not None
        self.workers[worker_id] = wh
        return wh

    def _container_cmd(self, spec: dict, env: Dict[str, str],
                       cwd: str, extra_keys: set = frozenset()) -> list:
        """Worker launch line for a container runtime env (reference:
        runtime_env/container.py — podman run with the session mounted).
        Host IPC + host network keep the shm object store and the TCP
        control plane working unchanged inside the container; the ray_tpu
        package and session dir are bind-mounted so the image only needs
        a python. The runtime binary is injectable ('runtime' in the
        spec), which is also how tests exercise this path without a
        container engine."""
        runtime = spec["runtime"]
        cmd = [runtime, "run", "--rm", "--ipc=host", "--network=host",
               "-w", cwd]
        mounts = {self.session_dir, spec.get("pkg_root") or "", cwd}
        for m in sorted(m for m in mounts if m):
            cmd += ["-v", f"{m}:{m}"]
        for k, v in sorted(env.items()):
            # Runtime plumbing + the user's own runtime_env env_vars
            # (extra_keys) — a dropped user var would fail silently
            # inside the container.
            if (k.startswith(("RAY_TPU_", "JAX_")) or k == "PYTHONPATH"
                    or k in extra_keys):
                cmd += ["-e", f"{k}={v}"]
        cmd += spec.get("run_options", [])
        cmd += [spec["image"], "python", "-m",
                "ray_tpu._private.worker_main"]
        return cmd

    # --- log access (reference: dashboard state head log streaming;
    # `ray logs` lists/reads node log files via the node's agent) -------
    def _log_dir(self) -> str:
        return os.path.join(self.session_dir, "logs")

    async def h_list_logs(self, conn, p):
        """Log filenames on this node, with sizes (reference: state API
        list_logs — per-node file listing, optionally glob-filtered)."""
        import fnmatch
        pat = (p or {}).get("glob") or "*"
        log_dir = self._log_dir()
        out = []
        try:
            for name in sorted(os.listdir(log_dir)):
                if fnmatch.fnmatch(name, pat):
                    try:
                        size = os.path.getsize(os.path.join(log_dir, name))
                    except OSError:
                        continue
                    out.append({"name": name, "size": size})
        except FileNotFoundError:
            pass
        return out

    async def h_read_log(self, conn, p):
        """Tail of one log file (reference: state API get_log).  `lines`
        caps the tail; reads are bounded to 4 MiB so a runaway log can't
        blow the RPC frame."""
        name = os.path.basename(p["name"])    # no path traversal
        path = os.path.join(self._log_dir(), name)
        lines = int(p.get("lines", 1000))
        if lines <= 0:
            return ""                 # [-0:] would be the WHOLE file
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                cap = 4 << 20
                if size > cap:
                    f.seek(size - cap)
                data = f.read(cap)
        except OSError:
            return None
        text = data.decode("utf-8", "replace")
        return "\n".join(text.splitlines()[-lines:])

    async def h_register_worker(self, conn, p):
        wh = self.workers.get(p["worker_id"])
        if wh is None:
            raise rpc.RpcError("unknown worker")
        wh.address = tuple(p["address"])
        wh.conn = conn
        # Do NOT override conn.on_close here: the server installed its
        # close chain (connection-set cleanup + _on_client_close lease
        # reclaim).  A worker's registration conn is the same conn it
        # later requests leases over — clobbering the chain made every
        # lease held by a killed worker leak its CPUs permanently.
        wh.registered.set()
        return {"node_id": self.node_id}

    async def _pop_worker(self, env_extra=None,
                          needs_tpu: bool = False,
                          cwd: str | None = None) -> WorkerHandle:
        """Reuse an idle pooled worker or spawn one (reference:
        WorkerPool::PopWorker, worker_pool.h:55; reuse keyed by runtime env —
        round 1 pools only default-env workers).  CPU and TPU workers pool
        separately: CPU workers spawn without the accelerator client (fast
        startup, no chip state); TPU workers keep it."""
        if not env_extra and cwd is None:
            pool = self.idle_tpu_workers if needs_tpu else self.idle_workers
            while pool:
                wh = pool.pop()
                if wh.proc.poll() is None and wh.conn and not wh.conn.closed:
                    return wh
        wh = await self._spawn_worker(env_extra, needs_tpu=needs_tpu,
                                      cwd=cwd)
        cfg = get_config()
        deadline = time.monotonic() + cfg.worker_register_timeout_s
        while True:
            try:
                await asyncio.wait_for(wh.registered.wait(), 0.5)
                return wh
            except asyncio.TimeoutError:
                # A worker killed between spawn and registration (crash,
                # chaos SIGKILL) must fail the grant NOW — waiting out the
                # full registration timeout stalls the lease request (and
                # its parked successors) for a minute.
                if wh.proc.poll() is not None:
                    raise rpc.RpcError(
                        f"worker died during startup (exit "
                        f"{wh.proc.returncode})")
                if time.monotonic() >= deadline:
                    wh.proc.kill()
                    raise rpc.RpcError("worker failed to register in time")

    @staticmethod
    def _try_acquire_from(avail: Dict[str, float],
                          resources: Dict[str, float]) -> bool:
        if not all(avail.get(k, 0.0) >= v - 1e-9 for k, v in resources.items()
                   if v > 0):
            return False
        for k, v in resources.items():
            avail[k] = avail.get(k, 0.0) - v
        return True

    def _try_acquire(self, resources: Dict[str, float]) -> bool:
        return self._try_acquire_from(self.resources_available, resources)

    def _release_resources(self, resources: Dict[str, float],
                           bundle_key: Optional[Tuple[bytes, int]] = None):
        """Return lease resources to their pool: the PG bundle they came
        from (if it still exists — a removed bundle already gave the node
        pool its total back), else the node pool."""
        if bundle_key is not None:
            bundle = self.bundles.get(bundle_key)
            if bundle is not None:
                for k, v in resources.items():
                    bundle["available"][k] = bundle["available"].get(k, 0.0) + v
                return
            # Bundle was removed while the lease ran: its unused part went
            # back to the node pool at return_bundle; the lease's share
            # comes back here.
        for k, v in resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0.0) + v
        self._kick_parked()

    # -------------------------------------------------------------- leasing --
    async def h_request_lease(self, conn, p):
        """Grant a worker lease, reply spillback with a better node, or —
        when this node is saturated with a feasible shape and nowhere to
        spill — PARK the request and reply when resources free up
        (reference: NodeManager::HandleRequestWorkerLease
        node_manager.cc:1776; the raylet's ClusterLeaseManager queues
        leases and the RPC replies on grant, it never tells a feasible
        client to poll)."""
        rec = frec.recorder()
        t0 = rec.begin()
        req_epoch = p.get(protocol.EPOCH_KEY)
        if isinstance(req_epoch, int):
            if req_epoch > self.cluster_epoch:
                # The requester heard about a failover before this agent's
                # next heartbeat did — adopt its epoch rather than reject
                # a perfectly current owner.
                self._learn_epoch(req_epoch)
            elif (req_epoch != protocol.EPOCH_NONE
                  and req_epoch < self.cluster_epoch):
                # Fencing: the owner is still living in a pre-failover
                # epoch.  A typed rejection (not a plain refusal) tells it
                # to refresh its epoch and resubmit — retried work stays
                # exactly-once because nothing was granted here.
                return {"granted": False,
                        "reject": protocol.REJECT_STALE_EPOCH,
                        protocol.EPOCH_KEY: self.cluster_epoch}
        if not (self._parked_leases and not p.get("placement_group")):
            # Fast path only while nobody is parked: a fresh request must
            # not jump the FIFO, or a stream of small shapes starves a
            # parked large one forever (the drain loop grants in order).
            res = await self._try_grant_lease(conn, p)
            if res is not None:
                if isinstance(res, dict) and res.get("granted"):
                    rec.end("lease", "lease:grant", t0,
                            id=res.get("lease_id") or b"")
                return res
        # Queued: the span covers queued -> granted (or refused), with
        # the queue depth at park time — the lease-lifecycle leg of the
        # flight recorder (prefetch/push/RUNNING continue it).
        depth = len(self._parked_leases)
        fut = asyncio.get_running_loop().create_future()
        deadline = time.monotonic() + float(p.get("max_park_s", 60.0))
        self._parked_leases.append((p, conn, fut, deadline))
        self._kick_parked()
        res = await fut
        if isinstance(res, dict) and res.get("granted"):
            rec.end("lease", "lease:queued", t0,
                    id=res.get("lease_id") or b"", depth=depth)
        return res

    async def _try_grant_lease(self, conn, p):
        """One grant attempt. Returns a reply dict, or None when the
        request should park (feasible here, saturated, no spillback)."""
        resources = p.get("resources", {})
        if self._draining is not None:
            # Draining nodes accept no new work; point the submitter at a
            # live peer so its lease pump re-routes instead of spinning
            # (reference: raylet lease rejection while draining).
            spill = None
            if not p.get("placement_group"):
                spill = await self._find_spillback(resources,
                                                   p.get("prefetch"))
            return {"granted": False,
                    "reason": f"node draining ({self._draining})",
                    "spillback": spill, "retry_after_ms": 200}
        if not p.get("placement_group"):
            # Memory-pressure lease shedding: when the node's shared
            # pressure signal (arena occupancy / node RAM past the OOM
            # threshold / KV pool / chaos squeeze) is high, prefer a
            # feasible peer over piling more working set onto a node
            # already evicting.  Only when a spillback target EXISTS —
            # a sole node always grants (degrading to refusal would
            # deadlock single-node clusters, and the create queue
            # already backpressures the data plane).
            try:
                from .memory_monitor import pressure_signal
                level = pressure_signal().level()
            except Exception:
                level = 0.0
            if level >= self._shed_threshold:
                spill = await self._find_spillback(resources,
                                                   p.get("prefetch"))
                if spill is not None:
                    self._leases_shed += 1
                    return {"granted": False, "spillback": spill,
                            "reason": "memory pressure shed"}
        pg = p.get("placement_group")
        bundle_key = None
        if pg:
            # Leases inside a PG draw from the bundle's reservation, not the
            # node pool (reference: bundle resources become `CPU_group_*`
            # resources the lease consumes instead of the node's).
            bundle_key = self._find_bundle(pg["pg_id"],
                                           pg.get("bundle_index", 0),
                                           resources)
            if bundle_key is None:
                return {"granted": False,
                        "reason": "bundle not on this node or exhausted",
                        "retry_after_ms": 100}
            acquired = self._try_acquire_from(
                self.bundles[bundle_key]["available"], resources)
        else:
            acquired = self._try_acquire(resources)
        if not acquired:
            if pg:
                # Bundle exhausted: generic spillback would point off-PG;
                # the client retries (rotating bundles for index -1).
                return {"granted": False, "reason": "bundle exhausted",
                        "retry_after_ms": 100}
            spill = await self._find_spillback(resources,
                                               p.get("prefetch"))
            if spill is not None:
                return {"granted": False, "spillback": spill}
            if all(self.resources_total.get(k, 0.0) >= v - 1e-9
                   for k, v in resources.items() if v > 0):
                return None          # feasible but busy: park
            return {"granted": False, "reason": "infeasible",
                    "retry_after_ms": 100}
        # Runtime-env materialization NEVER blocks the grant RPC: a pip
        # install can take minutes while the client's lease timeout is
        # ~130s — a blocked handler whose client gave up would still
        # grant, leaking the lease (reference: the raylet delegates to
        # the runtime-env agent and retries the lease).
        status, payload = self.uri_cache.poll_setup(
            self.gcs, p.get("runtime_env"))
        if status == "pending":
            self._release_resources(resources, bundle_key)
            return {"granted": False,
                    "reason": "runtime env setup in progress",
                    "retry_after_ms": 1000}
        if status == "failed":
            self._release_resources(resources, bundle_key)
            return {"granted": False,
                    "reason": f"runtime env setup failed: {payload}",
                    "retry_after_ms": 200}
        env_extra, cwd = payload
        env_extra = dict(env_extra)
        try:
            if p.get("env"):
                env_extra.update(p["env"])
            wh = await self._pop_worker(
                env_extra or None, needs_tpu=_needs_tpu(resources), cwd=cwd)
        except Exception as e:
            # A spawn failure must release the acquired resources.
            self._release_resources(resources, bundle_key)
            return {"granted": False, "reason": str(e), "retry_after_ms": 200}
        if conn.closed:
            # The requester died while this grant was in flight (worker
            # spawn can take seconds) — its disconnect cleanup already
            # ran, so a grant recorded now would leak these resources
            # forever.  Hand everything back instead; the reply goes
            # nowhere anyway.
            self._release_resources(resources, bundle_key)
            self._recycle_worker(wh)
            return {"granted": False, "reason": "client disconnected"}
        lease_id = os.urandom(16)
        wh.lease_id = lease_id
        wh.lease_resources = resources
        wh.lease_bundle = bundle_key
        wh.lease_owner_conn = conn
        wh.lease_granted_at = time.monotonic()
        wh.lease_stall_flagged = False
        wh.granted_epoch = self.cluster_epoch
        self.leases[lease_id] = wh
        if p.get("prefetch"):
            # Arg prefetch: start pulling the lease's missing large
            # by-ref args NOW, so the fetch overlaps the submitter's
            # push round-trip and the worker's dispatch/queueing
            # (reference: the raylet pulls task-arg bundles during
            # lease setup).  Fire-and-forget — the executing task's own
            # resolve joins the in-flight pull (or finds the object
            # landed) via the pull dedup table.
            rpc.spawn(self._prefetch_lease_args(p["prefetch"]))
        return {"granted": True, "lease_id": lease_id,
                "worker_addr": list(wh.address),
                "worker_id": wh.worker_id,
                protocol.EPOCH_KEY: self.cluster_epoch}

    async def _prefetch_lease_args(self, entries) -> None:
        cfg = get_config()
        if not cfg.arg_prefetch_enabled:
            return
        for ent in entries:
            try:
                oid, locs, owner, size, task_id = ent
                oid = bytes(oid)
            except (TypeError, ValueError):
                continue
            if self.store.contains(oid) or oid in self.spilled or \
                    oid in self._pull_inflight:
                continue
            # Visible in the task timeline BEFORE the worker picks the
            # task up: the acceptance signal that fetch overlapped
            # dispatch.
            self._note_task_event(bytes(task_id), "PREFETCH")
            rpc.spawn(self._prefetch_one(oid, locs, owner))

    async def _prefetch_one(self, oid: bytes, locs, owner) -> None:
        try:
            with frec.recorder().span("lease", "prefetch", id=oid):
                await self.h_pull_object(None, {
                    "object_id": oid,
                    "from_addrs": [list(a) for a in locs or ()],
                    "owner_addr": list(owner) if owner else None,
                    "priority": 2})
        except Exception:
            # Best-effort: the task's own arg resolution retries and,
            # failing that, the owner-mediated fetch path decides.
            pass

    def _note_task_event(self, task_id: bytes, event: str) -> None:
        if self.gcs is None or self.gcs.closed:
            return
        try:
            self.gcs.notify("task_events", {"events": [{
                "task_id": task_id, "name": "", "event": event,
                "ts": clocks.wall(), "worker_id": b"",
                "node_id": self.node_id, "job_id": b""}]})
        except rpc.RpcError:
            pass

    def _kick_parked(self):
        """Resources were released somewhere: let the drain loop retry."""
        if self._parked_leases:
            self._park_event.set()

    async def _parked_lease_loop(self):
        """Single drainer (serialization avoids double-granting the head):
        grants parked lease requests FIFO as resources free up. Strict
        FIFO per node matches the reference's queue and avoids starving
        large shapes behind a stream of small ones."""
        while not self._shutdown:
            try:
                await asyncio.wait_for(self._park_event.wait(), timeout=1.0)
            except asyncio.TimeoutError:
                pass               # periodic pass: expire deadlines
            self._park_event.clear()
            q = self._parked_leases
            while q:
                p, conn, fut, deadline = q[0]
                if fut.done() or conn.closed:
                    q.popleft()
                    continue
                if time.monotonic() > deadline:
                    q.popleft()
                    if not fut.done():
                        fut.set_result({"granted": False,
                                        "reason": "saturated",
                                        "retry_after_ms": 100})
                    continue
                try:
                    res = await self._try_grant_lease(conn, p)
                except Exception as e:  # noqa: BLE001 — reply, don't die
                    res = {"granted": False, "reason": str(e),
                           "retry_after_ms": 200}
                if res is None:
                    break          # still saturated: wait for the next kick
                # The head may have been popped by _on_client_close while
                # _try_grant_lease awaited; only pop if it's still us.
                if q and q[0][2] is fut:
                    q.popleft()
                if not fut.done():
                    fut.set_result(res)
                elif res.get("granted"):
                    # Nobody is listening for this grant anymore.
                    wh = self.leases.get(res["lease_id"])
                    if wh is not None:
                        self._reclaim_lease(res["lease_id"], wh)

    def _find_bundle(self, pg_id: bytes, bundle_index: int,
                     resources: Dict[str, float]
                     ) -> Optional[Tuple[bytes, int]]:
        """Resolve a bundle key on this node; -1 = any bundle with room."""
        if bundle_index >= 0:
            key = (pg_id, bundle_index)
            return key if key in self.bundles else None
        for key, bundle in self.bundles.items():
            if key[0] != pg_id:
                continue
            avail = bundle["available"]
            if all(avail.get(k, 0.0) >= v - 1e-9
                   for k, v in resources.items() if v > 0):
                return key
        return None

    async def _find_spillback(self, resources,
                              prefetch=None) -> Optional[list]:
        """Pick a better node from the GCS resource view (stands in for
        the reference's in-raylet cluster view synced by ray_syncer),
        scored by the hybrid top-k policy
        (reference: hybrid_scheduling_policy.h:50). The view is cached
        ~500ms — under saturation every lease request lands here, and the
        reference's syncer view is likewise eventually consistent.

        `prefetch` (the lease request's arg work list) doubles as a
        locality hint: within the same trusted+feasible tier, spill
        toward the node already holding the task's bytes — locality is
        a tiebreak below feasibility and trust, never above."""
        from . import scheduling_policy as policy
        now = time.monotonic()
        if now - getattr(self, "_nodes_cache_ts", 0.0) > 0.5:
            try:
                self._nodes_cache = await self.gcs.call("get_nodes", {})
                self._nodes_cache_ts = time.monotonic()
            except rpc.RpcError:
                return None
        nodes = self._nodes_cache
        loc_map = {}
        if prefetch and get_config().object_locality_scheduling_enabled:
            for ent in prefetch:
                try:
                    _oid, locs, _owner, size, _tid = ent
                except (TypeError, ValueError):
                    continue
                for a in locs or ():
                    key = tuple(a)
                    loc_map[key] = loc_map.get(key, 0) + int(size or 0)
        # Gray-suspect nodes are spilled to only when nothing healthy
        # FITS — try the trusted subset first, then fall back to every
        # live node (mirroring the GCS scheduler: a suspect node is a
        # last resort, never a hard exclusion — if only it has room the
        # lease must spill there, not park forever).
        live = [n for n in nodes
                if policy.targetable(n)
                and bytes(n["node_id"]) != self.node_id]
        trusted = policy.prefer_trusted(live)
        for group in ([trusted, live] if len(trusted) < len(live)
                      else [live]):
            if loc_map:
                best = policy.pick_by_locality(
                    [(tuple(n["address"]), tuple(n["address"]),
                      n["resources_total"], n["resources_available"])
                     for n in group],
                    resources, loc_map,
                    min_bytes=get_config().object_locality_min_bytes)
                if best:
                    return list(best)
            cands = [(tuple(n["address"]), n["resources_total"],
                      n["resources_available"]) for n in group]
            best = policy.hybrid_pick(cands, resources)
            if best:
                return list(best)
        return None

    async def h_profile_worker(self, conn, p):
        """Forward a live-profiling request to workers on this node
        (reference: the reporter agent launching py-spy/memray against
        worker pids, dashboard/modules/reporter/profile_manager.py).
        kind: 'stacks' | 'cpu_profile'; worker_id None = every live
        registered worker."""
        kind = p.get("kind", "stacks")
        if kind not in ("stacks", "cpu_profile"):
            raise rpc.RpcError(f"unknown profile kind {kind!r}")
        payload = {"duration_s": p.get("duration_s", 5.0)}
        targets = []
        want = p.get("worker_id")
        for wid, wh in self.workers.items():
            if want is not None and wid != want:
                continue
            if wh.conn is None or wh.conn.closed or wh.proc.poll() is not None:
                continue
            targets.append((wid, wh))
        out = {}
        results = await asyncio.gather(
            *[wh.conn.call(kind, payload,
                           timeout=float(p.get("duration_s", 5.0)) + 30)
              for _, wh in targets],
            return_exceptions=True)
        for (wid, _), res in zip(targets, results):
            out[wid.hex()] = (
                {"error": str(res)} if isinstance(res, BaseException)
                else res)
        return out

    async def h_node_profile(self, conn, p):
        """Whole-node live profile for the GCS cluster_profile fan-out:
        the agent's own stacks/CPU profile + every live worker's,
        sampled CONCURRENTLY so the node is one coherent time window.
        A worker dying mid-profile yields a typed per-worker error
        entry, never a failed fan-out."""
        kind = p.get("kind", "stacks")
        if kind not in ("stacks", "cpu_profile"):
            raise rpc.RpcError(f"unknown profile kind {kind!r}")
        pid = p.get("pid")
        payload = {"duration_s": p.get("duration_s", 2.0),
                   "interval_s": p.get("interval_s", 0.01)}

        async def _self_profile():
            try:
                if kind == "stacks":
                    r = diagnosis.dump_stacks()
                else:
                    r = await diagnosis.cpu_profile(payload["duration_s"],
                                                    payload["interval_s"])
                r["daemon"] = "agent"
                return r
            except Exception as e:  # noqa: BLE001 — typed entry, not a crash
                return {"error": str(e)}

        async def _one_worker(wid, wh):
            try:
                return wid, await wh.conn.call(
                    kind, payload,
                    timeout=float(payload["duration_s"]) + 30)
            except Exception as e:  # noqa: BLE001
                return wid, {"error": str(e)}

        targets = []
        for wid, wh in self.workers.items():
            if wh.conn is None or wh.conn.closed \
                    or wh.proc.poll() is not None:
                continue
            if pid is not None and wh.proc.pid != int(pid):
                continue
            targets.append((wid, wh))
        include_agent = pid is None or int(pid) == os.getpid()
        coros = [_one_worker(wid, wh) for wid, wh in targets]
        if include_agent:
            coros.append(_self_profile())
        results = await asyncio.gather(*coros)
        out = {"node_id": self.node_id.hex(), "workers": {}}
        if include_agent:
            out["agent"] = results.pop()
        for wid, res in results:
            out["workers"][wid.hex()] = res
        return out

    # ------------------------------------------------------- diagnosis ---
    def _send_anomaly(self, info: dict) -> None:
        """Best-effort forward to the GCS anomaly sink (triggers the
        black-box capture); the counter + recorder event were already
        emitted process-locally by record_anomaly."""
        if self.gcs is None or self.gcs.closed:
            return
        try:
            self.gcs.notify("report_anomaly", info)
        except rpc.RpcError:
            pass

    def _anomaly_from_thread(self, info: dict) -> None:
        loop = getattr(self, "_loop", None)
        if loop is None:
            return
        try:
            loop.call_soon_threadsafe(self._send_anomaly, info)
        except RuntimeError:
            pass

    def _recycle_worker(self, wh: WorkerHandle):
        """Return a no-longer-leased worker to its idle pool, or
        terminate it.  Runtime-env workers are never pooled: their
        env_vars / PYTHONPATH / cwd would leak into default-env tasks."""
        wh.last_idle = time.monotonic()
        pool = self.idle_tpu_workers if wh.needs_tpu else self.idle_workers
        if (wh.proc.poll() is None and not wh.is_actor and not wh.has_env
                and len(pool) < IDLE_WORKER_KEEP):
            pool.append(wh)
        elif not wh.is_actor:
            wh.proc.terminate()

    def _on_client_close(self, conn):
        """A lease client (driver/worker) disconnected: reclaim every
        lease it still holds — a driver exiting mid-lease must not leak
        node resources (reference: raylet lease cleanup on disconnect)."""
        for item in self._parked_leases:
            # Resolve parked requests from this client so their handler
            # coroutines don't wait forever; the drain loop reaps entries.
            if item[1] is conn and not item[2].done():
                item[2].set_result({"granted": False,
                                    "reason": "client disconnected"})
        for lease_id, wh in list(self.leases.items()):
            if wh.lease_owner_conn is conn:
                self._reclaim_lease(lease_id, wh)

    def _reclaim_lease(self, lease_id: bytes, wh: WorkerHandle):
        """Forcibly return a lease whose owner is gone.  Settles blocked-get
        CPU accounting (a blocked worker's CPU was already handed back by
        h_worker_blocked — returning the full grant would double-credit
        the pool)."""
        self.leases.pop(lease_id, None)
        self._release_resources(self._settle_lease_release(wh),
                                wh.lease_bundle)
        wh.lease_id = None
        wh.lease_resources = {}
        wh.lease_bundle = None
        wh.lease_owner_conn = None
        self._recycle_worker(wh)

    async def h_return_lease(self, conn, p):
        # Returns are accepted under ANY epoch: refusing a release from a
        # pre-failover owner would leak the worker forever, and handing
        # resources back is safe regardless of who asks.  (Grants are the
        # fenced direction — see h_request_lease.)
        e = p.get(protocol.EPOCH_KEY)
        if isinstance(e, int):
            self._learn_epoch(e)
        wh = self.leases.get(p["lease_id"])
        if wh is None:
            return False
        self._reclaim_lease(p["lease_id"], wh)
        return True

    def _settle_lease_release(self, wh: WorkerHandle) -> Dict[str, float]:
        """Resources a finishing/dying lease should hand back: the lease's
        grant minus any CPU already released by a blocked get still
        outstanding (a worker can die mid-get; its CPU must not be
        returned twice)."""
        res = wh.lease_resources
        if wh.blocked_depth > 0 and wh.blocked_cpus:
            res = dict(res)
            res["CPU"] = res.get("CPU", 0.0) - wh.blocked_cpus
        wh.blocked_depth = 0
        wh.blocked_cpus = 0.0
        return res

    async def h_worker_blocked(self, conn, p):
        """A leased worker blocked inside ray_tpu.get: release its CPU so
        queued/parked work (often the very task it waits on) can run here
        (reference: NotifyDirectCallTaskBlocked — the raylet releases CPU
        but never accelerators; a TPU worker blocked in get keeps its
        chip)."""
        wh = self.workers.get(p["worker_id"])
        if wh is None or wh.lease_id is None:
            return False
        wh.blocked_depth += 1
        if wh.blocked_depth == 1:
            cpus = wh.lease_resources.get("CPU", 0.0)
            if cpus > 0:
                wh.blocked_cpus = cpus
                self._release_resources({"CPU": cpus}, wh.lease_bundle)
        return True

    async def h_worker_unblocked(self, conn, p):
        """The blocked get returned: take the CPU back. The pool may go
        NEGATIVE here (other work was granted the freed CPU meanwhile) —
        that's deliberate oversubscription-then-backpressure, matching the
        reference: no new grants until the pool recovers, but the resumed
        task is never made to wait for its own CPU (deadlock)."""
        wh = self.workers.get(p["worker_id"])
        if wh is None or wh.blocked_depth <= 0:
            return False
        wh.blocked_depth -= 1
        if wh.blocked_depth == 0 and wh.blocked_cpus:
            cpus, wh.blocked_cpus = wh.blocked_cpus, 0.0
            pool = None
            if wh.lease_bundle is not None:
                bundle = self.bundles.get(wh.lease_bundle)
                if bundle is not None:
                    pool = bundle["available"]
            if pool is None:
                pool = self.resources_available
            pool["CPU"] = pool.get("CPU", 0.0) - cpus
        return True

    # --------------------------------------------------------------- actors --
    async def h_create_actor_worker(self, conn, p):
        """Lease a dedicated worker and instantiate the actor in it
        (reference: GcsActorScheduler leasing from raylet + PushTask of the
        creation task)."""
        if self._draining is not None:
            raise rpc.RpcError(f"node draining ({self._draining})")
        # Idempotence across GCS restarts: if this actor already has a
        # live worker here (the previous create's reply was lost with the
        # GCS), return it instead of leasing a second process.
        for wh in self.leases.values():
            if (wh.is_actor and wh.actor_id == p["actor_id"]
                    and wh.conn and not wh.conn.closed):
                return {"worker_addr": list(wh.address),
                        "worker_id": wh.worker_id}
        resources = p.get("resources", {})
        strategy = p.get("scheduling_strategy") or {}
        bundle_key = None
        if strategy.get("type") == "placement_group":
            bundle_key = self._find_bundle(
                strategy["pg_id"], strategy.get("bundle_index", 0), resources)
            if bundle_key is None:
                raise rpc.RpcError("PG bundle not on this node or exhausted")
            acquired = self._try_acquire_from(
                self.bundles[bundle_key]["available"], resources)
        else:
            acquired = self._try_acquire(resources)
        if not acquired:
            raise rpc.RpcError("insufficient resources for actor")
        # Same non-blocking env contract as h_request_lease: the GCS
        # scheduler retries while a pip install runs in the background.
        status, payload = self.uri_cache.poll_setup(
            self.gcs, p.get("runtime_env"))
        if status != "ready":
            self._release_resources(resources, bundle_key)
            raise rpc.RpcError(
                "runtime env setup in progress" if status == "pending"
                else f"runtime env setup failed: {payload}")
        env_extra, cwd = payload
        try:
            wh = await self._pop_worker(dict(env_extra) or None,
                                        needs_tpu=_needs_tpu(resources),
                                        cwd=cwd)
        except Exception:
            self._release_resources(resources, bundle_key)
            raise
        wh.is_actor = True
        wh.actor_id = p["actor_id"]
        wh.lease_id = os.urandom(16)
        wh.lease_resources = resources
        wh.lease_bundle = bundle_key
        self.leases[wh.lease_id] = wh
        try:
            await wh.conn.call("actor_init", p, timeout=115)
        except (rpc.RpcError, asyncio.TimeoutError) as e:
            self._release_resources(resources, bundle_key)
            self.leases.pop(wh.lease_id, None)
            # Clear lease fields so _on_worker_death doesn't release again
            # — and the ACTOR fields, or the death watcher races this
            # raise with a generic actor_failed("exited with code 0")
            # that masks the real __init__ error (e.g. an unimportable
            # actor class) at the caller.
            wh.lease_id = None
            wh.lease_resources = {}
            wh.lease_bundle = None
            wh.is_actor = False
            wh.actor_id = None
            wh.proc.terminate()
            raise rpc.RpcError(f"actor __init__ failed: {e}")
        return {"worker_addr": list(wh.address), "worker_id": wh.worker_id}

    async def h_actor_worker_died(self, conn, p):
        await self.gcs.call("actor_failed", p)
        return True

    # ------------------------------------------------------ graceful drain --
    async def h_drain(self, conn, p):
        """Agent half of the two-phase node drain (GCS h_drain_node):
        stop granting leases (parked requests resolve with spillback),
        migrate pinned primary objects to a live peer, then wait — bounded
        by the deadline — for in-flight non-actor leases to finish.  Actor
        workers keep serving until the final teardown: the GCS restarts
        their actors elsewhere concurrently, and clients fail over on
        connection loss."""
        reason = p.get("reason") or "manual"
        deadline = time.monotonic() + float(p.get("deadline_s", 30.0))
        if self._draining is None:
            self._draining = reason
            logger.warning("node %s draining (%s)",
                           self.node_id.hex()[:8], reason)
            self._kick_parked()
            # Swarm-source handoff: withdraw every secondary-replica
            # registration NOW, so new pulls stop routing here.  The
            # copies keep serving in-flight chunk requests until
            # teardown; mid-stream pulls fail over to the remaining
            # holders when this node finally goes away.
            for oid in list(self._replica_owner):
                self._drop_replica_registration(oid)
        self._drain_deadline = max(self._drain_deadline, deadline)
        migrated = await self._migrate_primaries(deadline)
        while time.monotonic() < deadline:
            if not any(not wh.is_actor for wh in self.leases.values()):
                break
            await asyncio.sleep(0.1)
        # Second pass: leases that finished during the wait may have
        # pinned fresh task returns; push those off-node too.
        migrated += await self._migrate_primaries(deadline)
        busy = sum(1 for wh in self.leases.values() if not wh.is_actor)
        return {"migrated": migrated, "busy_leases": busy}

    async def _migrate_primaries(self, deadline: float) -> int:
        """Republish this node's pinned primary copies to a live peer and
        record each move in the GCS KV (ns 'migrated') so owners repoint
        instead of running destructive lineage re-execution.  Spilled
        primaries migrate the same way — the peer pulls them straight out
        of the spill file via the chunked transfer path."""
        oids = [oid for oid in list(self.pinned)
                if oid not in self._migrated_away]
        if not oids:
            return 0
        try:
            nodes = await self.gcs.call("get_nodes", {})
        except (rpc.RpcError, asyncio.TimeoutError):
            return 0
        from . import scheduling_policy as policy
        peers = [n for n in nodes
                 if policy.targetable(n)
                 and bytes(n["node_id"]) != self.node_id]
        if not peers:
            logger.warning(
                "drain: no live peer for %d pinned primaries; owners fall "
                "back to external restore or lineage re-execution",
                len(oids))
            return 0
        migrated = 0
        for i, oid in enumerate(oids):
            if time.monotonic() >= deadline:
                break
            for attempt in range(len(peers)):
                n = peers[(i + attempt) % len(peers)]
                addr = tuple(n["address"])
                conns = await self._pull_peers([addr])
                if not conns:
                    continue
                timeout = max(1.0, min(60.0, deadline - time.monotonic()))
                owner = self._pinned_owner.get(oid)
                try:
                    ok = await conns[0].call("adopt_primary", {
                        "object_id": oid,
                        "from_addrs": [list(self.address)],
                        # The adoptive node repoints the owner's replica
                        # directory directly (primary=True add) — owners
                        # learn the new home without waiting for a
                        # recovery probe or the migrated-KV fallback.
                        "owner_addr": list(owner) if owner else None,
                        "priority": 0}, timeout=timeout)
                except (rpc.RpcError, asyncio.TimeoutError):
                    continue
                if not ok:
                    continue
                # Record the destination BEFORE the KV write: even if the
                # write fails (owners then fall back to lineage), a later
                # migration pass must not re-adopt at a different peer and
                # orphan this pinned copy, and frees must still forward.
                self._migrated_away[oid] = addr
                try:
                    await self.gcs.call("kv_put", {
                        "ns": "migrated", "key": oid.hex(),
                        "value": json.dumps(list(addr)).encode(),
                        "overwrite": True})
                except (rpc.RpcError, asyncio.TimeoutError):
                    break    # copy exists but owners can't find it; move on
                migrated += 1
                break
        return migrated

    async def h_adopt_primary(self, conn, p):
        """Become the primary holder of an object migrating off a draining
        node: pull the bytes (shm, or disk when the arena is full), take
        one owner pin so they can't be evicted before the owner repoints,
        and remember the adoption so a later free also clears the
        cluster-wide 'migrated' KV record."""
        oid = p["object_id"]
        if not await self.h_pull_object(conn, p):
            return False
        self._disk_cached.pop(oid, None)   # a primary now, not a cache
        if not await self.h_pin_object(conn, {"object_id": oid}):
            return False
        self._adopted.add(oid)
        owner = p.get("owner_addr")
        if owner:
            # Promote in the owner's replica directory: this node is the
            # primary now (any stale secondary record of us collapses
            # into it), so subsequent pulls/frees route straight here.
            self._pinned_owner[oid] = tuple(owner)
            self._replica_owner.pop(oid, None)
            rpc.spawn(self._notify_owner_location(oid, tuple(owner),
                                                  add=True, primary=True))
        return True

    async def _forward_free(self, addr: tuple, oid: bytes) -> None:
        try:
            conns = await self._pull_peers([tuple(addr)])
            if conns:
                await conns[0].call("free_objects", {"object_ids": [oid]})
        except (rpc.RpcError, asyncio.TimeoutError):
            pass

    # ------------------------------------------------------ placement groups --
    def _reserve_one(self, pg_id: bytes, bundle_index: int,
                     resources: Dict[str, float]) -> Optional[bool]:
        """Acquire + record ONE PG bundle reservation. Returns True on a
        fresh reservation, None when already present (idempotent retry),
        False when resources don't fit."""
        key = (pg_id, bundle_index)
        if key in self.bundles:
            return None
        if self._draining is not None:
            return False        # no new reservations on a departing node
        if not self._try_acquire(resources):
            return False
        self.bundles[key] = {"total": dict(resources),
                             "available": dict(resources)}
        return True

    async def h_prepare_bundle(self, conn, p):
        return self._reserve_one(p["pg_id"], p["bundle_index"],
                                 p["resources"]) is not False

    async def h_reserve_bundles(self, conn, p):
        """Single-node PG fast path: prepare+commit every bundle in ONE
        RPC.  The two-phase protocol exists for cross-node atomicity
        (reference: node_manager.proto:471-476); with all bundles on one
        node there is no second participant, so the round trips collapse.
        All-or-nothing: a failed acquire rolls back this call's own
        reservations (bundles already present from a retried call are
        kept)."""
        acquired = []
        for b in p["bundles"]:
            got = self._reserve_one(p["pg_id"], b["bundle_index"],
                                    b["resources"])
            if got is False:
                for k in acquired:
                    self._release_resources(self.bundles.pop(k)["total"])
                return False
            if got:
                acquired.append((p["pg_id"], b["bundle_index"]))
        return True

    async def h_commit_bundle(self, conn, p):
        return (p["pg_id"], p["bundle_index"]) in self.bundles

    async def h_return_bundle(self, conn, p):
        bundle = self.bundles.pop((p["pg_id"], p["bundle_index"]), None)
        if bundle:
            # Only the unused part returns now; resources still held by
            # running leases come back to the node pool as each lease
            # returns (see _release_resources fallthrough) — never
            # double-counted against physical chips.
            self._release_resources(bundle["available"])
        return True

    # -------------------------------------------------------------- objects --
    async def h_pin_object(self, conn, p):
        """Owner-requested pin of a primary copy (reference: raylet
        PinObjectIDs keeping plasma objects alive for their owner)."""
        oid = p["object_id"]
        if p.get("owner_addr"):
            # Who to tell when a drain migrates this primary elsewhere.
            self._pinned_owner[oid] = tuple(p["owner_addr"])
        if oid in self.spilled:
            self.pinned[oid] = self.pinned.get(oid, 0) + 1
            return True
        if self.store.get(oid, timeout_ms=0) is None:
            return False
        self.pinned[oid] = self.pinned.get(oid, 0) + 1
        await self._maybe_spill_to_threshold()
        return True

    async def h_pin_transfer(self, conn, p):
        """Adopt a writer-held pin (one-way notify from the put/return hot
        path). The writer stored with keep_pin, so one shm refcount is
        already in place — this is pure bookkeeping: record it as an owner
        pin so unpin/free release it, exactly as if h_pin_object had taken
        it. Spilled-to-disk primaries carry no shm refcount but use the
        same pinned accounting (h_unpin_object/h_free_objects check
        self.spilled before touching the store)."""
        oid = p["object_id"]
        if p.get("owner_addr"):
            self._pinned_owner[oid] = tuple(p["owner_addr"])
        self.pinned[oid] = self.pinned.get(oid, 0) + 1
        # The create this pin finalizes is sealed: its admission
        # reservation (if any) collapses into the store's real
        # accounting.
        self._release_reservation(oid)
        if oid in self.spilled:
            # Spilled before (or during) the ownership handoff — e.g. a
            # worker's direct put-to-disk whose owner we only learn now:
            # register the storage-tier directory location.
            owner = self._pinned_owner.get(oid)
            if owner is not None:
                rpc.spawn(self._notify_owner_location(
                    oid, owner, add=True, disk=True))
        await self._maybe_spill_to_threshold()
        return True

    async def h_unpin_object(self, conn, p):
        oid = p["object_id"]
        n = self.pinned.get(oid, 0)
        if n <= 1:
            self.pinned.pop(oid, None)
            self._pinned_owner.pop(oid, None)
        else:
            self.pinned[oid] = n - 1
        if n >= 1 and oid not in self.spilled:
            self.store.release(oid)
        return True

    async def h_free_objects(self, conn, p):
        for oid in p["object_ids"]:
            self._release_reservation(oid)
            for _ in range(self.pinned.pop(oid, 0)):
                if oid not in self.spilled:
                    self.store.release(oid)
            spill = self.spilled.pop(oid, None)
            self._disk_cached.pop(oid, None)
            self._pinned_owner.pop(oid, None)
            # Deregister with the owner: for owner-initiated frees the
            # directory entry is already gone (the remove is a no-op),
            # but a direct free (tools/bench) must not leave the owner
            # pointing at bytes we just dropped.
            self._drop_replica_registration(oid)
            if spill is not None:
                try:
                    os.unlink(spill[0])
                except FileNotFoundError:
                    pass
            self._ext_delete(oid)
            self.store.delete(oid)
            if oid in self._adopted:
                # Adopted-from-drain primary freed: clear the cluster-wide
                # migration record so nothing repoints to freed bytes.
                self._adopted.discard(oid)
                if self.gcs is not None:
                    rpc.spawn(self.gcs.call(
                        "kv_del", {"ns": "migrated", "key": oid.hex(),
                                   "prefix": False}))
            dest = self._migrated_away.pop(oid, None)
            if dest is not None:
                # Freed on the draining source after migration: forward so
                # the adopted copy (and its pin) can't leak at the peer.
                rpc.spawn(self._forward_free(dest, oid))
        return True

    def _ext_delete(self, oid: bytes) -> None:
        """Best-effort removal of an object's durable external copy + its
        GCS registration (freed objects must not accumulate in the cloud
        tier)."""
        if self._ext is None:
            return
        uri = self._ext_uris.pop(oid, None)
        if uri is None:
            return
        try:
            self._ext.delete(uri)
        except Exception:
            logger.exception("external spill delete failed for %s",
                             oid.hex())
        if self.gcs is not None:
            rpc.spawn(self.gcs.call(
                "kv_del", {"ns": "spill_ext", "key": oid.hex(),
                           "prefix": False}))

    # --- spilling (reference: local_object_manager.h:43 + plasma
    # create_request_queue backpressure) ------------------------------------
    def _spill_path(self, oid: bytes) -> str:
        os.makedirs(self._spill_dir, exist_ok=True)
        return os.path.join(self._spill_dir, oid.hex())

    async def _spill_one(self, oid: bytes) -> int:
        """Move one pinned primary to disk. Returns bytes freed (0 = not
        spillable right now: unsealed, or a reader outside our pins).
        The file write runs off-loop; the delete is atomic against readers
        (release_n_and_delete_if) so a worker that pins mid-write keeps a
        valid object and the spill aborts."""
        if oid in self.spilled or oid in self._spilling:
            return 0
        npins = self.pinned.get(oid, 0)
        view = self.store.get(oid, timeout_ms=0)
        if view is None:
            return 0
        if self.store.refcount(oid) > npins + 1:  # fast-path skip: reader active
            view.release()
            self.store.release(oid)
            return 0
        self._spilling.add(oid)
        size = len(view)
        try:
            path = self._spill_path(oid)
        except OSError:
            # Spill dir unusable (unwritable/clobbered): the object is
            # simply not spillable right now — the sweep must degrade,
            # not crash the admission loops that drive it.
            self._spilling.discard(oid)
            view.release()
            self.store.release(oid)
            return 0
        try:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, _write_file, path, view)
        except OSError:
            view.release()
            self.store.release(oid)
            return 0
        finally:
            self._spilling.discard(oid)
            view.release()
        if self.pinned.get(oid, 0) != npins:
            # The pin count moved while the write ran off-loop (a second
            # put's pin_transfer, a fresh owner pin, or an unpin): the
            # snapshot release_n_and_delete_if would commit is STALE —
            # releasing n+1 here would either strip a pin someone still
            # counts on or leave the arena copy undeletable with broken
            # accounting.  Abort this sweep's attempt; the object is
            # still resident and a later sweep re-snapshots.
            self.store.release(oid)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return 0
        if not self.store.release_n_and_delete_if(oid, npins + 1):
            # A reader pinned the object mid-write: abort the spill.
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return 0
        self.spilled[oid] = (path, size)
        self._spilled_bytes_total += size
        # The spilled primary becomes a STORAGE-TIER directory location
        # at its owner: pulls/recovery resolve through it (this agent
        # serves the file via fetch_chunk / restores direct-to-arena),
        # and locality scoring discounts it to DISK_TIER_WEIGHT.
        owner = self._pinned_owner.get(oid)
        if owner is not None:
            rpc.spawn(self._notify_owner_location(oid, owner, add=True,
                                                  disk=True))
        if self._ext is not None:
            # Synchronous: the object is not durably spilled until the
            # external copy exists (the reference's cloud spill IS the
            # spill write, not a background mirror).
            await self._ext_upload(oid, path)
        return size

    async def _free_space(self, need: int) -> int:
        """Make `need` bytes of arena headroom, cheapest eviction first.

        Ordering (the tiered-memory eviction policy, test-pinned):
        1. DROP local secondaries — replicas this node pulled from an
           owner elsewhere.  The swarm can re-fetch them from the
           primary at any time, so deleting one costs a future pull,
           never durability, and no disk write.
        2. SPILL sole pinned primaries oldest-first — each costs an
           NVMe write and makes THIS node the only restore source — but
           never below `eviction_pinned_bytes_floor` of arena-resident
           pinned bytes (a hot working set stays mapped even under
           admission pressure).
        Unpinned sealed objects are already LRU-evicted by the store
        itself on allocation pressure."""
        freed = 0
        try:
            objs = {o: (sz, rc) for o, sz, rc in self.store.list_objects()}
        except Exception:
            objs = {}
        for oid in list(self._replica_owner.keys()):
            if freed >= need:
                return freed
            info = objs.get(oid)
            if info is None or oid in self.pinned or oid in self.spilled:
                continue
            size, rc = info
            if rc != 0:
                continue            # a reader holds it right now
            try:
                self.store.delete(oid)
            except Exception:
                continue
            self._drop_replica_registration(oid)
            freed += size
        if freed >= need:
            return freed
        floor = self._pinned_floor
        resident = 0
        if floor > 0:
            resident = sum(objs.get(o, (0, 0))[0] for o in self.pinned
                           if o not in self.spilled)
        for oid in list(self.pinned.keys()):
            if freed >= need:
                break
            if floor > 0 and \
                    resident - objs.get(oid, (0, 0))[0] < floor:
                continue
            got = await self._spill_one(oid)
            freed += got
            if floor > 0:
                resident -= got
        return freed

    def _capacity_scale(self) -> float:
        """mem_chaos hook: fraction of real capacity the admission/spill
        policy may use right now (1.0 = no squeeze)."""
        return (self._mem_chaos.arena_frac()
                if self._mem_chaos is not None else 1.0)

    def _reserved_bytes(self) -> int:
        """Unexpired admission reservations (granted creates not yet
        sealed) — counted as in-use by every policy decision.  Expiry
        (writer crashed between reserve and seal) is swept here."""
        if not self._reserved:
            return 0
        now = time.monotonic()
        for o in [o for o, (_, exp) in self._reserved.items()
                  if exp < now]:
            del self._reserved[o]
        return sum(n for n, _ in self._reserved.values())

    def _arena_pressure(self, st=None) -> float:
        """Occupancy-with-reservations over EFFECTIVE capacity in
        [0, 1] — the arena's contribution to the shared pressure
        signal."""
        if st is None:
            try:
                st = self.store.stats()
            except Exception:
                return 0.0
        cap = max(1, int(st.get("capacity", 1) * self._capacity_scale()))
        used = st.get("bytes_in_use", 0) + self._reserved_bytes()
        return min(1.0, used / cap)

    async def _maybe_spill_to_threshold(self):
        st = self.store.stats()
        cap = int(st["capacity"] * self._capacity_scale())
        target = int(cap * self._spill_threshold)
        usage = st["bytes_in_use"] + self._reserved_bytes()
        if usage > target:
            await self._free_space(usage - target)

    async def h_ensure_space(self, conn, p):
        """Create-queue backpressure: a writer that got ENOMEM asks us to
        spill; it retries its create afterwards."""
        return {"freed": await self._free_space(int(p["nbytes"]))}

    # --- create admission (the CreateRequestQueue analogue; reference:
    # plasma create_request_queue.h — creates QUEUE for headroom instead
    # of failing, and fail TYPED past their deadline) --------------------
    def _retry_after_s(self) -> float:
        """Backoff hint for a refused create: scales with queue depth so
        a deeper backlog spreads retries wider."""
        return min(5.0, 0.1 * (1 + len(self._create_queue)))

    def _admit_now(self, oid: bytes, nbytes: int) -> bool:
        """Reserve `nbytes` of headroom for `oid` if it fits RIGHT NOW
        under effective capacity minus in-use minus prior reservations.
        The reservation makes admission atomic: a racing create cannot
        be granted the same headroom, and pressure sweeps count it as
        in-use so they never target the headroom an unsealed in-progress
        region is about to occupy."""
        try:
            st = self.store.stats()
        except Exception:
            return False
        cap = int(st["capacity"] * self._capacity_scale())
        headroom = cap - st["bytes_in_use"] - self._reserved_bytes()
        if nbytes > headroom:
            return False
        self._reserved[oid] = (nbytes, time.monotonic() + 60.0)
        return True

    def _release_reservation(self, oid: bytes) -> None:
        if self._reserved.pop(oid, None) is not None and self._reserved:
            self._create_event.set()

    async def h_reserve_create(self, conn, p):
        """Admission control for a put/return seal: reserve arena
        headroom, parking FIFO (bounded) while eviction/spill makes
        room.  Reply {"ok": True} = reserved, go store; {"ok": False,
        "retry_after_s": ...} = refused typed — the caller surfaces
        ObjectStoreFullError(retry_after_s), NEVER a raw arena error."""
        oid = p["object_id"]
        nbytes = int(p["nbytes"])
        deadline = time.monotonic() + float(
            p.get("timeout_s") or get_config().create_backpressure_timeout_s)
        # Fast path only when nothing is parked: FIFO order is the
        # anti-starvation guarantee (a stream of small puts must not
        # starve the big create at the head of the queue).
        if not self._create_queue and self._admit_now(oid, nbytes):
            return {"ok": True}
        if not self._create_queue:
            await self._free_space(nbytes)
            if self._admit_now(oid, nbytes):
                return {"ok": True}
        if len(self._create_queue) >= self._create_queue_depth_max:
            return {"ok": False, "reason": "queue_full",
                    "retry_after_s": self._retry_after_s()}
        fut = asyncio.get_running_loop().create_future()
        self._create_queue.append((oid, nbytes, deadline, fut))
        self._create_event.set()
        return await fut

    async def _create_queue_loop(self):
        """FIFO drainer for parked creates: retries the HEAD as
        eviction/spill/frees make headroom, expires entries typed at
        their deadline."""
        while not self._shutdown:
            if not self._create_queue:
                self._create_event.clear()
                await self._create_event.wait()
                continue
            oid, nbytes, deadline, fut = self._create_queue[0]
            if fut.done():
                self._create_queue.popleft()
                continue
            if time.monotonic() >= deadline:
                self._create_queue.popleft()
                fut.set_result({"ok": False, "reason": "deadline",
                                "retry_after_s": self._retry_after_s()})
                continue
            if self._admit_now(oid, nbytes):
                self._create_queue.popleft()
                fut.set_result({"ok": True})
                continue
            try:
                await self._free_space(nbytes)
            except Exception:
                logger.exception("create-queue eviction pass failed")
            if self._admit_now(oid, nbytes):
                self._create_queue.popleft()
                fut.set_result({"ok": True})
                continue
            # No headroom yet: wait for a free/unpin/chaos-restore tick.
            await asyncio.sleep(0.05)

    async def h_spill_path(self, conn, p):
        """Hand a worker the path for a direct put-to-disk (objects that can
        never fit the arena). The worker writes the file itself — same host,
        shared filesystem — so no copy crosses the RPC."""
        return self._spill_path(p["object_id"])

    async def h_spill_register(self, conn, p):
        oid = p["object_id"]
        path = self._spill_path(oid)
        if not os.path.exists(path):
            return False
        size = os.path.getsize(path)
        self.spilled[oid] = (path, size)
        self._spilled_bytes_total += size
        self._release_reservation(oid)
        owner = (tuple(p["owner_addr"]) if p.get("owner_addr")
                 else self._pinned_owner.get(oid))
        if owner is not None:
            self._pinned_owner.setdefault(oid, owner)
            rpc.spawn(self._notify_owner_location(oid, owner, add=True,
                                                  disk=True))
        if self._ext is not None:
            await self._ext_upload(oid, path)
        return True

    async def _ext_upload(self, oid: bytes, path: str) -> None:
        """Push a freshly-spilled object to the durable tier and register
        its URI in the GCS KV (any node can then restore it)."""
        loop = asyncio.get_running_loop()
        try:
            data = await loop.run_in_executor(None, _read_file, path)
            uri = await loop.run_in_executor(
                None, self._ext.spill, oid.hex(), data)
        except Exception:
            logger.exception("external spill upload failed for %s",
                             oid.hex())
            return
        if oid not in self.spilled:
            # Freed (or restored-and-freed) while uploading.
            try:
                self._ext.delete(uri)
            except Exception:
                pass
            return
        self._ext_uris[oid] = uri
        if self.gcs is not None:
            try:
                await self.gcs.call("kv_put", {
                    "ns": "spill_ext", "key": oid.hex(),
                    "value": uri.encode(), "overwrite": True})
            except rpc.RpcError:
                logger.warning("could not register external spill of %s",
                               oid.hex())

    def _reacquire_pins(self, oid: bytes) -> bool:
        """Re-take this agent's owner pins on a just-restored object.
        False (with any partial pins dropped) if the object vanished
        mid-way — callers must then treat the restore as failed rather
        than deleting the durable copy of an evicted object."""
        need = self.pinned.get(oid, 0)
        for i in range(need):
            if self.store.get(oid, timeout_ms=0) is None:
                for _ in range(i):
                    self.store.release(oid)
                return False
        return True

    def _put_restored(self, oid: bytes, data: bytes) -> bool:
        """Insert restored bytes into shm + re-acquire this agent's pins.
        The writer pin is held across the re-pin so there is no
        zero-refcount window in which the fresh copy could be evicted."""
        held = False
        try:
            self.store.put(oid, [data], keep_pin=True)
            held = True
        except ObjectExistsError:
            pass
        except Exception:
            return False
        ok = self._reacquire_pins(oid)
        if held:
            self.store.release(oid)
        return ok

    async def _restore_from_external(self, oid: bytes) -> bool:
        """Pull a durable copy registered by ANY node (possibly dead) out
        of the external tier (reference: spilled-object URLs resolvable
        cluster-wide via external_storage.py)."""
        if self._ext is None:
            return False
        uri = self._ext_uris.get(oid)
        if uri is None and self.gcs is not None:
            try:
                v = await self.gcs.call(
                    "kv_get", {"ns": "spill_ext", "key": oid.hex()})
            except rpc.RpcError:
                return False
            if v is None:
                return False
            uri = v.decode() if isinstance(v, (bytes, bytearray)) else v
        if uri is None:
            return False
        loop = asyncio.get_running_loop()
        try:
            data = await loop.run_in_executor(None, self._ext.restore, uri)
        except Exception:
            # A transiently unreachable tier (NFS blip, backend IOError)
            # must read as "not restorable" so callers fall back to
            # lineage — not as an RPC error surfacing in a user get().
            logger.exception("external restore failed for %s", oid.hex())
            return False
        if data is None:
            return False
        # This agent now co-owns the durable copy: record its URI so a
        # later free from HERE also reclaims the cloud object + KV key
        # (the spiller node may be dead — cross-node restores must not
        # leak the external tier).
        self._ext_uris[oid] = uri
        for _ in range(3):
            if self._put_restored(oid, data):
                return True
            if await self._free_space(len(data)) == 0:
                break
        # Arena too contended to admit the object (live reader views make
        # primaries unspillable): re-materialize the local spill file so
        # readers can stream from it via the normal spilled-object path
        # (reference: spilled_object_reader.h).  Only this fallback pays
        # the disk write — the common uncontended restore stays in shm.
        path = self._spill_path(oid)
        try:
            await loop.run_in_executor(None, _write_file, path, data)
        except OSError:
            logger.exception("spill re-materialization failed for %s",
                             oid.hex())
            return False
        self.spilled[oid] = (path, len(data))
        return False  # callers fall back to streaming the spill file

    async def _restore_object(self, oid: bytes) -> bool:
        """Bring a spilled object back into shm (reference: raylet
        RestoreSpilledObject). Re-acquires the agent's pins; deletes the
        disk copy on success.  Falls back to the external tier when the
        local spill file is missing (e.g. restored on a different node
        than the spiller after a node death)."""
        spill = self.spilled.get(oid)
        if spill is None:
            if self.store.contains(oid):
                return True
            return await self._restore_from_external(oid)
        path, size = spill
        loop = asyncio.get_running_loop()
        # Zero-copy restore: the spill file is read DIRECTLY into the
        # object's freshly-allocated arena view (readinto — one pass from
        # the page cache, no intermediate Python bytes and no second
        # memcpy), off-loop so a multi-GB restore doesn't stall the agent.
        # keep_pin=True holds the writer pin across the executor->loop hop
        # so the fresh copy can't be evicted before the re-pin below.
        held = False
        for _ in range(3):
            try:
                await loop.run_in_executor(
                    None, lambda: self.store.read_file_into(
                        oid, path, size, keep_pin=True))
                held = True
                break
            except ObjectExistsError:
                break
            except FileNotFoundError:
                self.spilled.pop(oid, None)
                return await self._restore_from_external(oid)
            except StoreFullError:
                if await self._free_space(size) == 0:
                    return False
            except SpillTruncatedError:
                # The on-disk copy itself is damaged: freeing arena space
                # can't help — the durable external copy is the only way
                # back, and the broken file must be forgotten.
                logger.exception("spill file corrupt for %s", oid.hex())
                self.spilled.pop(oid, None)
                return await self._restore_from_external(oid)
            except OSError:
                # Transient I/O (EMFILE under fd churn, EIO blips): the
                # spill file is still the durable copy — KEEP the entry
                # and retry; dropping it would orphan valid bytes and
                # misreport the object as gone to remote pullers.
                logger.warning("transient I/O restoring %s; retrying",
                               oid.hex(), exc_info=True)
        else:
            return False
        ok = self._reacquire_pins(oid)
        if held:
            self.store.release(oid)
        if not ok:
            # Evicted out from under us (pre-existing copy raced an
            # eviction): keep the spill file — it is the durable copy.
            return False
        self.spilled.pop(oid, None)
        self._disk_cached.pop(oid, None)
        self._restored_bytes_total += size
        # Back in the arena: retract the storage-tier directory marking
        # (disk=True removes ONLY the tier record — this node's
        # primary/secondary entry stands, now at full arena weight).
        owner = self._pinned_owner.get(oid)
        if owner is not None:
            rpc.spawn(self._notify_owner_location(oid, owner, add=False,
                                                  disk=True))
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        return True

    async def h_restore_object(self, conn, p):
        return await self._restore_object(p["object_id"])

    # --- transfer (reference: object_manager.cc chunked push/pull) ----------
    async def h_fetch_from_store(self, conn, p):
        """Whole-object fetch (small objects / compat path)."""
        oid = p["object_id"]
        if oid in self.spilled:
            path, _ = self.spilled[oid]
            try:
                with open(path, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                return None
        view = self.store.get(oid, timeout_ms=p.get("timeout_ms", 0))
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            view.release()
            self.store.release(oid)

    def _note_served(self, n: int) -> None:
        # Shard threads and the main loop both serve chunks; += on an
        # attribute is a read-modify-write that drops counts under races.
        with self._served_lock:
            self._bytes_served += n

    def _sh_fetch_chunk(self, conn, p):
        """SHARD-LOCAL fetch_chunk fast path (see _handlers wiring): a
        SEALED shm object is served straight off the connection's I/O
        shard — store lookup, arena subview pin, and the raw writev all
        stay on the shard thread, so N peers pulling N objects spread
        across cores instead of serializing on the agent's main loop.
        Anything stateful — spilled objects, mid-pull partial serves,
        gone-handling (directory retraction) — returns FAST_FALLBACK and
        takes the exact h_fetch_chunk path on the main loop."""
        # Bind every field BEFORE pinning (store.get): a malformed
        # request erroring after the pin would leak it permanently.
        oid, off, length = p["object_id"], p["offset"], p["length"]
        raw = p.get("raw", False)
        if oid in self.spilled:
            return rpc.FAST_FALLBACK
        view = self.store.get(oid, timeout_ms=0)
        if view is None:
            return rpc.FAST_FALLBACK
        self._note_served(min(length, max(0, len(view) - off)))
        if raw:
            piece = view[off:off + length]

            def _unpin(v=view, oid=oid):
                v.release()
                self.store.release(oid)

            return rpc.RawPayload([piece], release=_unpin)
        try:
            piece = bytes(view[off:off + length])
            rpc.note_copied_bytes("serve_legacy_chunk", len(piece))
            return piece
        finally:
            view.release()
            self.store.release(oid)

    async def h_object_info(self, conn, p):
        """Size + presence probe that precedes a chunked pull."""
        oid = p["object_id"]
        if oid in self.spilled:
            return {"size": self.spilled[oid][1], "spilled": True}
        part = self._partial.get(oid)
        if part is not None and part["size"] is not None:
            # Mid-pull here: peers may stripe committed chunks off us
            # (uncommitted ones answer "later" and fail over).  A
            # size-less marker (pull still probing) stays silent — we
            # know nothing a prober doesn't.
            return {"size": part["size"], "spilled": False,
                    "partial": True}
        view = self.store.get(oid, timeout_ms=p.get("timeout_ms", 0))
        if view is None:
            return None
        try:
            return {"size": len(view), "spilled": False}
        finally:
            view.release()
            self.store.release(oid)

    async def h_fetch_chunk(self, conn, p):
        """Serve one chunk of an object's bytes, from shm or the spill file.

        With p["raw"] the chunk leaves as a raw out-of-band frame: a shm
        chunk is handed to the transport as a pinned arena subview (zero
        user-space copies on this side; the pin drops once the transport
        has taken the bytes), and absence becomes the TYPED {"gone": True}
        marker so pullers can tell "source no longer holds it" from a
        dropped/failed fetch.  Legacy (non-raw) callers keep the old
        bytes-or-None contract."""
        oid, off, length = p["object_id"], p["offset"], p["length"]
        raw = p.get("raw", False)
        if oid in self.spilled:
            path, _ = self.spilled[oid]

            def _read_spill_chunk():
                try:
                    fd = os.open(path, os.O_RDONLY)
                except FileNotFoundError:
                    # A concurrent restore sealed the object back into
                    # shm and unlinked the file between our spilled-map
                    # read and the open: the caller falls through to the
                    # store lookup — answering "gone" would misroute the
                    # puller into lineage re-execution for an object
                    # this node still holds.
                    return None
                try:
                    return os.pread(fd, length, off)
                finally:
                    os.close(fd)

            # Off-loop: a cold-cache 8 MiB pread times the whole inflight
            # window would otherwise stall every RPC this agent serves.
            data = await asyncio.get_running_loop().run_in_executor(
                None, _read_spill_chunk)
            if data is not None:
                self._note_served(len(data))
                return rpc.RawPayload([data]) if raw else data
        view = self.store.get(oid, timeout_ms=0)
        if view is None:
            # Receiver-becomes-source: an arena pull of this object is in
            # flight here — serve the chunk if its bytes are already
            # committed (a COPY, never a subview: the unsealed buffer's
            # lifetime belongs to the pull, which may abort), else tell
            # the peer to come back ("later" — it retries its remaining
            # sources; the primary always has the bytes).
            part = self._partial.get(oid)
            if part is not None:
                if part["buf"] is not None and part["size"] is not None:
                    end = min(off + length, part["size"])
                    if _intervals_cover(part["done"], off, end):
                        piece = bytes(part["buf"][off:end])
                        rpc.note_copied_bytes("serve_partial_chunk",
                                              len(piece))
                        self._note_served(len(piece))
                        return rpc.RawPayload([piece]) if raw else piece
                return {"later": True} if raw else None
            # No copy at all: if the directory still lists us, retract
            # the registration (the copy was LRU-evicted) so pullers
            # stop being routed here.
            self._drop_replica_registration(oid)
            return {"gone": True} if raw else None
        self._note_served(min(length, max(0, len(view) - off)))
        if raw:
            piece = view[off:off + length]

            def _unpin(v=view, oid=oid):
                v.release()
                self.store.release(oid)

            return rpc.RawPayload([piece], release=_unpin)
        try:
            piece = bytes(view[off:off + length])
            rpc.note_copied_bytes("serve_legacy_chunk", len(piece))
            return piece
        finally:
            view.release()
            self.store.release(oid)

    async def _pull_slot(self, priority: int):
        """Priority-ordered admission to the pull pool (reference:
        pull_manager.cc bundle priorities: get > wait > task args)."""
        if self._pull_active < self._max_pulls:
            self._pull_active += 1
            return
        import heapq
        fut = asyncio.get_running_loop().create_future()
        self._pull_seq += 1
        heapq.heappush(self._pull_waiters, (priority, self._pull_seq, fut))
        await fut

    def _pull_done(self):
        import heapq
        if self._pull_waiters:
            _, _, fut = heapq.heappop(self._pull_waiters)
            if not fut.done():
                fut.set_result(None)
                return
        self._pull_active -= 1

    async def h_pull_object(self, conn, p):
        """Fetch a remote object into the local store — chunked, pipelined,
        deduped against concurrent pulls of the same id, admission-
        controlled by priority (reference: pull_manager.cc, 806 LoC of
        priority logic).  `from_addrs` lists candidate source nodes in
        preference order (legacy single `from_addr` accepted); a chunk
        that fails mid-stream on one source fails over to the next
        instead of aborting the pull.  Returns True on success, False
        when every source reports the object gone; a TRANSIENT
        mid-stream failure raises ObjectTransferError (typed — never a
        truncated buffer, never a false \"lost\")."""
        oid = p["object_id"]
        if self.store.contains(oid) or oid in self.spilled:
            return True
        addrs = [tuple(a) for a in (p.get("from_addrs") or [])]
        if not addrs and p.get("from_addr"):
            addrs = [tuple(p["from_addr"])]
        addrs = [a for a in addrs if a != tuple(self.address)]
        owner = tuple(p["owner_addr"]) if p.get("owner_addr") else None
        if not addrs and owner is None:
            return False
        # End-to-end budget: explicit payload field, or the deadline the
        # RPC frame itself carried (rpc dispatch exposes it) — pulls
        # triggered inside a deadline-carrying call inherit the
        # REMAINING budget with zero caller changes.
        deadline = p.get("deadline") or rpc.current_handler_deadline()
        # Join a concurrent pull of the same object, but keep each
        # caller's OWN budget: a deadline-less joiner must not inherit
        # DeadlineExceededError when the running pull's (shorter) budget
        # expires — the object is healthy, so re-pull with our budget —
        # and a deadline-carrying joiner is bounded by wait_for even
        # when the running pull has no deadline at all.
        while True:
            entry = self._pull_inflight.get(oid)
            if entry is None:
                break
            fut, running_deadline = entry
            if deadline is not None:
                # Our own budget is checked OUTSIDE the try: the except
                # below routes the running pull's expiry to a re-pull,
                # and our own expiry must never take that branch (it
                # would loop without awaiting — a synchronous spin).
                left = deadline - time.time()
                if left <= -rpc.DEADLINE_SKEW_SLACK_S:
                    raise exc.DeadlineExceededError(
                        f"pull of {oid.hex()} exceeded its deadline "
                        f"while joining an in-flight pull")
            try:
                if deadline is None:
                    return await asyncio.shield(fut)
                return await asyncio.wait_for(asyncio.shield(fut),
                                              max(0.05, left))
            except asyncio.TimeoutError:
                raise exc.DeadlineExceededError(
                    f"pull of {oid.hex()} exceeded its deadline "
                    f"while joining an in-flight pull") from None
            except exc.DeadlineExceededError:
                if deadline is None or (
                        running_deadline is not None
                        and deadline > running_deadline + 1e-6):
                    continue  # its budget, not ours — pull ourselves
                raise
        fut = asyncio.get_running_loop().create_future()
        self._pull_inflight[oid] = (fut, deadline)
        rec = frec.recorder()
        t0 = rec.begin()
        try:
            ok = await self._do_pull(oid, addrs,
                                     p.get("priority", 0),
                                     p.get("timeout_ms", 10000),
                                     deadline=deadline, owner=owner)
            fut.set_result(ok)
            # Object-transfer timeline: one span per pull, start -> commit
            # (chunk-wave/hedge events nest inside it, keyed by the same
            # object id).
            rec.end("transfer", "pull", t0, id=oid, ok=bool(ok),
                    sources=self._last_pull_sources)
            return ok
        except Exception as e:
            fut.set_exception(e)
            # Mark retrieved: with no concurrent deduped waiter the future
            # is dropped, and an unconsumed exception (now routine —
            # transient failures raise ObjectTransferError by design)
            # would spam 'Future exception was never retrieved' at GC.
            fut.exception()
            raise
        finally:
            self._pull_inflight.pop(oid, None)

    class _ObjectGone(Exception):
        """Internal: every source reported the object absent."""

    async def _stream_chunks(self, peers, oid: bytes, size: int,
                             make_sink, commit=None,
                             deadline: float | None = None,
                             on_chunk=None) -> None:
        """Shared pipelined chunk engine for arena- and disk-destined
        pulls (and any future push path).  Keeps up to
        `object_transfer_max_inflight_chunks` fetch_chunk requests in
        flight so the source's shm/spill reads overlap the wire; each
        chunk lands via a raw out-of-band frame scattered straight into
        make_sink(pos, n) — no msgpack pass, no intermediate bytes.
        `commit(pos, data)` (optional coroutine) runs after a chunk fully
        lands — disk-destined pulls stage each chunk in memory and flush
        it off-loop there, so no blocking write ever runs on the agent
        loop; without commit the sink itself is the final destination
        (arena view).  `on_chunk(pos, n)` (optional, sync) fires once a
        chunk is final — the pull path publishes committed ranges there
        so this agent can serve them to swarm peers mid-pull.

        Swarm striping: with >=2 sources, chunk i's source order is the
        peer list ROTATED by i (round-robin) — N concurrent pullers of
        one object spread their chunk load across every holder instead
        of serializing on the first (Cornet-style broadcast; the owner
        already ordered the list primary-first, suspects last, and
        `_order_peers` folded in local link evidence).  A source that
        answers "later" (mid-pull peer that hasn't committed that chunk
        yet) is skipped for this attempt — the rotation always ends at
        a complete copy.

        Tail defense: with >=2 sources and budget left in the hedge
        bucket, the first attempt of each chunk RACES a backup source
        started after the primary's observed p95 latency — first
        responder wins, the straggler is cancelled (its late bytes are
        discarded by call_raw's sink defusal, so the two writers can
        never interleave into the destination).  An end-to-end
        `deadline` (absolute wall clock) caps every attempt's timeout by
        the remaining budget and raises DeadlineExceededError when it
        runs out.

        Failure discipline: a failed chunk retries on each source in turn
        (two passes).  Raises _ObjectGone when every source consistently
        answers \"gone\", ObjectTransferError when transient failures
        (drops, timeouts, short reads) exhaust the retry budget — callers
        abort the destination, so a partial pull can never be mistaken
        for complete data."""
        if size == 0:
            return

        def budget_timeout() -> float:
            if deadline is None:
                return self._chunk_timeout
            rem = deadline - time.time()
            # Slack: the deadline may have been stamped by a remote
            # owner's clock.  Within the skew window attempts continue
            # on a short floor; retry exhaustion past the deadline still
            # classifies as a deadline failure below.
            if rem <= -rpc.DEADLINE_SKEW_SLACK_S:
                raise exc.DeadlineExceededError(
                    f"pull of {oid.hex()} exceeded its deadline")
            return min(self._chunk_timeout, max(rem, 0.25))

        async def try_peer(peer, pos: int, n: int, sink_obj,
                           eff_timeout: float):
            """One fetch attempt -> ('ok'|'gone'|'dead'|'transient', err).
            'dead' == source unreachable: its copy is lost for our
            purposes (must route to ObjectLost -> lineage recovery, not
            to a retryable transient that never reconstructs)."""
            if peer is None or peer.closed:
                return "dead", None
            t0 = time.monotonic()
            try:
                res = await peer.call_raw(
                    "fetch_chunk",
                    {"object_id": oid, "offset": pos,
                     "length": n, "raw": True},
                    sink=sink_obj, timeout=eff_timeout)
            except rpc.ConnectionLost as e:
                self._note_peer_failure(peer)
                return "dead", e
            except (rpc.RpcError, asyncio.TimeoutError) as e:
                self._note_peer_failure(peer)
                return "transient", e
            if isinstance(res, int) and res == n:
                self._note_peer_latency(peer, time.monotonic() - t0, n,
                                        chunk=True)
                return "ok", None
            if isinstance(res, (bytes, bytearray)):
                # Legacy peer: msgpack bytes body.
                if len(res) == n:
                    sink_obj[0:n] = res
                    rpc.note_copied_bytes("pull_legacy_chunk", n)
                    self._note_peer_latency(peer, time.monotonic() - t0,
                                            n, chunk=True)
                    return "ok", None
                return "transient", ValueError(f"short chunk {len(res)}/{n}")
            if isinstance(res, dict) and res.get("later"):
                # Mid-pull peer hasn't committed this chunk yet: not a
                # failure (no health penalty), just not a source for
                # THIS chunk right now.
                return "later", None
            if res is None or (isinstance(res, dict) and res.get("gone")):
                return "gone", None
            return "transient", ValueError(
                f"unexpected fetch_chunk reply {type(res)}")

        async def settle(task):
            """Cancel-and-await a straggler attempt: call_raw's finally
            defuses its reception, so once this returns its late bytes
            can only be discarded — never scattered into a buffer the
            winner already filled.  Our OWN cancellation (this whole
            fetch aborted by a sibling chunk's failure) is re-raised —
            but only after the straggler is done — rather than
            swallowed: a worker that survives cancel would keep
            scattering remote bytes into arena regions the aborted
            pull has already released for reuse."""
            if not task.done():
                task.cancel()
            external = None
            while True:
                try:
                    await task
                except asyncio.CancelledError:
                    if not task.done():
                        # Injected into US mid-await (the straggler is
                        # still running, so it can't be the source).
                        # Remember it and keep waiting the straggler
                        # out — its cancel is already requested.
                        external = asyncio.CancelledError()
                        continue
                except Exception:  # noqa: BLE001
                    pass
                break
            if external is not None:
                raise external

        async def hedged(pos: int, n: int, ordered) -> bool:
            """Primary-vs-delayed-backup race; True = chunk landed.
            `ordered` is this chunk's striped source order — its head is
            the chunk's assigned source, the backup comes from the
            rest."""
            primary = ordered[0]
            backup = next((p for p in ordered[1:]
                           if p is not None and not p.closed), None)
            if backup is None or primary is None or primary.closed:
                return False
            eff = budget_timeout()
            sink1 = make_sink(pos, n)
            t1 = rpc.spawn(try_peer(primary, pos, n, sink1, eff))
            t2 = None
            # try/finally: budget expiry mid-race, or this whole fetch
            # being cancelled by a sibling chunk's failure, must never
            # leave an un-settled attempt scattering into the real sink
            # after the pull aborts and the arena region is reused.
            try:
                delay = min(self._hedge_delay_s(primary), eff)
                done, _ = await asyncio.wait({t1}, timeout=delay)
                if t1 in done:
                    st, _err = t1.result()
                    if st == "ok":
                        if commit is not None:
                            await commit(pos, sink1)
                        return True
                    return False   # sequential pass classifies/retries
                if not self._hedge_allow():
                    st, _err = await t1
                    if st == "ok":
                        if commit is not None:
                            await commit(pos, sink1)
                        return True
                    return False
                staging = memoryview(bytearray(n))
                frec.recorder().instant("transfer", "hedge_fired",
                                        id=oid, offset=pos)
                t2 = rpc.spawn(try_peer(backup, pos, n, staging,
                                        budget_timeout()))
                winner = None
                pending = {t1, t2}
                while pending and winner is None:
                    done, pending = await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED)
                    for t in done:
                        if t.result()[0] == "ok":
                            winner = t
                            break
                # Settled BEFORE touching sink1 below (not only in the
                # finally): the loser must be done writing first.
                await settle(t1)
                await settle(t2)
                if winner is None:
                    return False
                if winner is t2:
                    # Backup won into its private staging buffer; the
                    # primary is fully settled, so the real sink is ours.
                    rpc.note_copied_bytes("pull_hedge_staging", n)
                    if commit is None:
                        sink1[0:n] = staging
                    else:
                        await commit(pos, staging)
                    return True
                if commit is not None:
                    await commit(pos, sink1)
                return True
            finally:
                # Settle BOTH stragglers even if our own cancellation
                # lands mid-settle; propagate it only once neither can
                # write another byte.
                external = None
                for t in (t1, t2):
                    if t is None:
                        continue
                    try:
                        await settle(t)
                    except asyncio.CancelledError as e:
                        external = e
                if external is not None:
                    raise external

        # Per-node stripe phase: with N pullers and N holders, chunk i's
        # PRIMARY-assigned owner is unique per puller (k = (i + phase)
        # mod n), so in the cold concurrent phase the origin serves each
        # chunk ~once instead of N times — the swarm then exchanges the
        # rest peer-to-peer (Cornet partitions the chunk space the same
        # way before receivers gossip).
        phase = int.from_bytes(getattr(self, "node_id", b"")[:2] or b"\0",
                               "little") if len(peers) > 1 else 0

        async def fetch(pos: int) -> None:
            n = min(self._chunk_bytes, size - pos)
            # Round-robin stripe: chunk i's preferred source rotates
            # through the holder set, so concurrent pulls of one object
            # form a swarm instead of a convoy on the first source.
            k = (pos // self._chunk_bytes + phase) % len(peers)
            ordered = peers[k:] + peers[:k]
            self._hedge_total += 1
            if self._hedge_enabled and len(peers) >= 2:
                if await hedged(pos, n, ordered):
                    if on_chunk is not None:
                        on_chunk(pos, n)
                    return
            last_err = None
            gone = dead = transient = 0
            for _round in range(2):
                gone = dead = transient = 0
                for peer in ordered:
                    sink_obj = make_sink(pos, n)
                    st, err = await try_peer(peer, pos, n, sink_obj,
                                             budget_timeout())
                    if st == "ok":
                        if commit is not None:
                            await commit(pos, sink_obj)
                        if on_chunk is not None:
                            on_chunk(pos, n)
                        return
                    if st == "gone":
                        gone += 1
                    elif st == "dead":
                        dead += 1
                        last_err = err or last_err
                    else:
                        # "later" (mid-pull peer) counts with transient:
                        # that source still EXISTS, so an all-gone
                        # verdict (-> ObjectLost -> lineage) stays off
                        # the table while any swarm member remains.
                        transient += 1
                        if st == "later" and _round == 0:
                            # Give the mid-pull source a beat to commit
                            # before falling back — without it the cold
                            # phase of a broadcast degenerates to
                            # everyone re-converging on the origin.
                            await asyncio.sleep(0.02)
                        elif st != "later":
                            last_err = err or last_err
                if (gone or dead) and not transient:
                    # Unanimous and unambiguous: no second pass.
                    break
            if transient == 0:
                # Every source is gone or dead — the object is not
                # obtainable by retrying this pull.
                raise NodeAgent._ObjectGone(oid)
            if deadline is not None and time.time() > deadline:
                # Retries exhausted AND the budget ran out: the typed
                # deadline outcome, not a retryable transient — the
                # owner already wrote this pull off.
                raise exc.DeadlineExceededError(
                    f"pull of {oid.hex()} exceeded its deadline "
                    f"(chunk {pos}..{pos + n} unfetched after retries: "
                    f"{last_err!r})")
            raise exc.ObjectTransferError(
                f"chunk {pos}..{pos + n} of {oid.hex()} failed on all "
                f"{len(peers)} source(s) after retries: {last_err!r}")

        await rpc.gather_windowed(
            fetch, range(0, size, self._chunk_bytes),
            self._max_inflight_chunks)

    async def _pull_peers(self, addrs) -> list:
        """Resolve source addresses to live (cached) connections.  Each
        connection is tagged with its address (_peer_addr) so transfer
        paths can record per-peer latency/rate stats."""
        peers = []
        for addr in addrs:
            peer = self._peer_conns.get(addr)
            if peer is None or peer.closed:
                try:
                    peer = await rpc.connect(addr, name="agent->agent",
                                             retries=2)
                except rpc.ConnectionLost:
                    self._note_peer_failure(addr)
                    continue
                peer._peer_addr = addr
                self._peer_conns[addr] = peer
            peers.append(peer)
        return peers

    # ---------------------------------------------- replica directory -----
    async def _owner_conn(self, owner: tuple) -> rpc.Connection:
        """Connection to an object OWNER (a worker/driver process, not an
        agent) — shares the peer connection cache; owners and agents
        speak the same RPC layer."""
        conn = self._peer_conns.get(owner)
        if conn is None or conn.closed:
            conn = await rpc.connect(owner, name="agent->owner", retries=2)
            conn._peer_addr = owner
            self._peer_conns[owner] = conn
        return conn

    async def _merge_owner_locations(self, oid: bytes, addrs: list,
                                     owner: tuple,
                                     register: bool = False) -> list:
        """Union of the caller's from_addrs and the owner directory's
        CURRENT holder set (self excluded, caller's order preserved —
        the owner already ranks suspects last).  With register=True the
        same round trip records THIS node as a mid-pull secondary,
        atomically on the owner's loop — concurrent broadcast pullers
        discover each other through exactly this.  Best-effort: an
        unreachable owner just means no extra sources."""
        try:
            conn = await self._owner_conn(owner)
            res = await conn.call(
                "object_locations",
                {"object_id": oid,
                 "add_addr": list(self.address) if register else None},
                timeout=5)
        except (rpc.RpcError, asyncio.TimeoutError, OSError):
            return addrs
        if not res:
            return addrs
        merged = [tuple(a) for a in addrs]
        me = tuple(self.address)
        for a in res.get("locations") or ():
            t = tuple(a)
            if t != me and t not in merged:
                merged.append(t)
        return merged

    def _order_peers(self, peers: list) -> list:
        """Stable reorder of pull sources by LOCAL link evidence: peers
        with fresh failures sink to the back (the owner's ordering
        already put GCS-scored gray suspects last; this folds in what
        this node saw first-hand, e.g. a half-open link the GCS can't
        see from its vantage).  Freshness is judged on the FAILURE
        timestamp — successes clear the counter — so a long-healed peer
        is never punished for ancient blips."""
        def suspect(conn) -> int:
            st = self._peer_stats.get(getattr(conn, "_peer_addr", None))
            if not st:
                return 0
            return 1 if (st["fail"] >= 2
                         and time.monotonic()
                         - st.get("fail_ts", 0.0) < 60.0) else 0
        return sorted(peers, key=suspect)

    def _drop_replica_registration(self, oid: bytes) -> None:
        """Withdraw a secondary registration (eviction/abort/drain):
        directory entries must never outlive the bytes they point at."""
        owner = self._replica_owner.pop(oid, None)
        if owner is not None:
            rpc.spawn(self._notify_owner_location(oid, owner, add=False))

    async def _notify_owner_location(self, oid: bytes, owner: tuple,
                                     add: bool,
                                     primary: bool = False,
                                     disk: bool = False) -> None:
        try:
            conn = await self._owner_conn(tuple(owner))
            await conn.call(
                "object_location_add" if add else "object_location_remove",
                {"object_id": oid, "addr": list(self.address),
                 "primary": primary, "disk": disk}, timeout=10)
        except Exception:
            # Best-effort: a stale directory entry only costs a puller
            # one failed probe (it fails over); a dead owner means the
            # object is unreachable anyway.
            pass

    def _sweep_replica_registrations(self) -> None:
        """Deregister secondaries whose local copy silently vanished
        (shm LRU eviction happens inside the store, below this agent's
        sight) — rides the heartbeat tick; the fetch-chunk "gone" path
        catches the in-between window lazily."""
        for oid in list(self._replica_owner):
            if oid in self._partial or oid in self.spilled or \
                    self.store.contains(oid):
                continue
            self._drop_replica_registration(oid)

    # ---------------------------------------------- peer link health ------
    def _peer_stat(self, addr: tuple) -> dict:
        st = self._peer_stats.get(addr)
        if st is None:
            from collections import deque as _dq
            st = self._peer_stats[addr] = {
                "lat": _dq(maxlen=64), "rtt": None, "rate": None,
                "fail": 0, "fail_ts": 0.0, "ts": time.monotonic()}
        return st

    def _note_peer_latency(self, peer, dt: float, nbytes: int = 0, *,
                           chunk: bool = False) -> None:
        """Record a per-peer link observation.  chunk=True samples are
        bulk-transfer wall times: they feed the hedge-delay p95 deque
        and the transfer-rate EMA but NOT the rtt EMA — a chunk's
        duration is dominated by bandwidth and pipeline queuing, and
        folding it into 'rtt' would let the GCS's gray scorer (which
        compares against ~ms ping baselines) defame any node that
        merely serves bulk traffic.  Only round-trip-shaped samples
        (timed pings, _sample_peer_rtt) update 'rtt'."""
        addr = getattr(peer, "_peer_addr", None) if not isinstance(
            peer, tuple) else peer
        if addr is None:
            return
        st = self._peer_stat(addr)
        if chunk:
            st["lat"].append(dt)
            # A served chunk proves the link works NOW: clear the local
            # failure evidence so _order_peers judges the present, not
            # a healed blip.
            st["fail"] = 0
            if nbytes and dt > 0:
                rate = nbytes / dt
                st["rate"] = rate if st["rate"] is None \
                    else 0.8 * st["rate"] + 0.2 * rate
        else:
            st["rtt"] = dt if st["rtt"] is None \
                else 0.8 * st["rtt"] + 0.2 * dt
        st["ts"] = time.monotonic()

    async def _sample_peer_rtt(self, peer) -> None:
        """One timed ping — the only evidence allowed into the 'rtt'
        EMA.  Sampled once per probed peer per pull: cheap relative to
        any pull, and a delayed/congested link inflates it exactly when
        the gray scorer should hear about it."""
        if peer is None or peer.closed:
            return
        t0 = time.monotonic()
        try:
            await peer.call("ping", {}, timeout=5)
        except Exception:
            self._note_peer_failure(peer)
            # A lost ping is worst-case RTT evidence, not silence —
            # without this the lossiest link suppresses the very
            # samples that would indict it.
            self._note_peer_latency(peer, 5.0)
            return
        self._note_peer_latency(peer, time.monotonic() - t0)

    def _note_peer_failure(self, peer) -> None:
        addr = getattr(peer, "_peer_addr", None) if not isinstance(
            peer, tuple) else peer
        if addr is None:
            return
        st = self._peer_stat(addr)
        st["fail"] += 1
        st["fail_ts"] = st["ts"] = time.monotonic()

    def _peer_stats_snapshot(self) -> Dict[str, dict]:
        """Heartbeat payload: fresh (<60s) per-peer link observations,
        keyed 'host:port' (msgpack-safe), for the GCS's gray-failure
        scorer."""
        now = time.monotonic()
        # Evict long-dead entries (restarted peers bind fresh ports, so
        # addresses churn forever) — the 15 min horizon still preserves
        # hedge-delay p95 history across ordinary idle gaps.
        for addr in [a for a, st in self._peer_stats.items()
                     if now - st["ts"] > 900.0]:
            del self._peer_stats[addr]
        out = {}
        for addr, st in self._peer_stats.items():
            if now - st["ts"] > 60.0:
                continue
            # "fail" stays local (debugging): failed pings already fold
            # into the rtt EMA as worst-case samples, so shipping the
            # raw lifetime counter would be dead heartbeat payload.
            out[f"{addr[0]}:{addr[1]}"] = {
                "rtt": st["rtt"], "rate": st["rate"],
                "age_s": round(now - st["ts"], 3)}
        return out

    def _hedge_delay_s(self, peer) -> float:
        """How long to let the primary source run before racing a
        backup: its observed p95 chunk latency (x1.5 slack), the
        config override, or a 200ms cold-start default."""
        if self._hedge_delay_ms > 0:
            return self._hedge_delay_ms / 1000.0
        addr = getattr(peer, "_peer_addr", None)
        st = self._peer_stats.get(addr) if addr is not None else None
        if st and len(st["lat"]) >= 8:
            lat = sorted(st["lat"])
            p95 = lat[int(0.95 * (len(lat) - 1))]
            return min(p95 * 1.5 + 0.01, self._chunk_timeout / 2)
        return 0.2

    def _hedge_allow(self) -> bool:
        # Windowed budget (tail-at-scale hedge budgets are windowed for
        # this reason): halving both counters keeps the spend fraction
        # but caps how much credit a long healthy period can bank —
        # without the decay, a million quiet fetches would bankroll a
        # ~100k-hedge burst exactly when the cluster is already slow,
        # doubling load on it.
        if self._hedge_total >= 2048:
            self._hedge_total //= 2
            self._hedge_used //= 2
        if self._hedge_used <= (self._hedge_budget_frac
                                * self._hedge_total + 4):
            self._hedge_used += 1
            return True
        return False

    async def _do_pull(self, oid: bytes, addrs: list, priority: int,
                       timeout_ms: int,
                       deadline: float | None = None,
                       owner=None) -> bool:
        use_dir = owner is not None and \
            get_config().replica_directory_enabled
        ok = False
        if use_dir:
            # Announce this pull FIRST: the size-less partial marker
            # makes peers probing us answer "later" (not "gone"), and
            # the register-and-query round trip below both records us in
            # the owner's directory and returns the freshest holder set
            # — secondaries that registered since the caller stamped its
            # from_addrs, including peers MID-PULL right now.  That is
            # what turns N concurrent pulls of one object into a chunk
            # swarm instead of N convoys on the primary.
            self._partial.setdefault(
                oid, {"size": None, "buf": None, "done": []})
            self._replica_owner[oid] = tuple(owner)
            addrs = await self._merge_owner_locations(oid, addrs, owner,
                                                      register=True)
            # Swarm source set resolved (directory register-and-query):
            # the width here vs the caller's hint is the broadcast's
            # fan-in signature in the timeline.
            frec.recorder().instant("transfer", "swarm_sources", id=oid,
                                    sources=len(addrs))
        try:
            ok = await self._pull_into_node(oid, addrs, priority,
                                            timeout_ms, deadline, owner)
            return ok
        finally:
            if not ok and use_dir:
                # Withdraw the registration before the marker: directory
                # entries must not outlive what they point at.
                self._drop_replica_registration(oid)
            if not ok:
                self._partial.pop(oid, None)

    async def _pull_into_node(self, oid: bytes, addrs: list, priority: int,
                              timeout_ms: int, deadline, owner) -> bool:
        peers = await self._pull_peers(addrs)
        self._last_pull_sources = len(peers)
        if not peers:
            return False
        peers = self._order_peers(peers)
        await self._pull_slot(priority)
        try:
            if deadline is not None and \
                    deadline - time.time() <= -rpc.DEADLINE_SKEW_SLACK_S:
                raise exc.DeadlineExceededError(
                    f"pull of {oid.hex()} exceeded its deadline before "
                    f"the first probe")
            info = None
            for peer in peers:
                probe_timeout = 60 if deadline is None else \
                    max(0.1, min(60, deadline - time.time()))
                t0 = time.monotonic()
                try:
                    info = await peer.call(
                        "object_info",
                        {"object_id": oid, "timeout_ms": timeout_ms},
                        timeout=probe_timeout)
                except (rpc.RpcError, asyncio.TimeoutError):
                    self._note_peer_failure(peer)
                    continue
                # NOT a latency sample: object_info is a long-poll that
                # legitimately parks server-side up to timeout_ms while
                # the object is being created — time a dedicated ping
                # instead (the only evidence the rtt EMA accepts).
                rpc.spawn(self._sample_peer_rtt(peer))
                if info is not None:
                    break
            if info is None:
                return False
            size = info["size"]
            buf = None
            for attempt in range(3):
                try:
                    buf = self.store.create_buffer(oid, size)
                    break
                except ObjectExistsError:
                    return True
                except Exception:
                    if await self._free_space(size) == 0 and attempt:
                        break
            if buf is None:
                # No room even after spilling: land the pull on disk.
                return await self._pull_to_disk(peers, oid, size,
                                                deadline=deadline,
                                                owner=owner)
            # Receiver-becomes-source: publish committed ranges so peers
            # pulling the same object can stripe them off us mid-pull
            # (the directory registration happened at pull start).
            part = {"size": size, "buf": buf, "done": []}
            self._partial[oid] = part

            def on_chunk(pos, n, _done=part["done"], _sz=size):
                _intervals_add(_done, pos, min(pos + n, _sz))
                self._bytes_pulled += n

            ok = False
            try:
                # Chunk-wave span: strictly inside this pull's
                # start/commit span (the cross-node nesting property the
                # alignment test asserts).
                with frec.recorder().span("transfer", "chunks", id=oid,
                                          bytes=size,
                                          sources=len(peers)):
                    await self._stream_chunks(
                        peers, oid, size,
                        make_sink=lambda pos, n: buf[pos:pos + n],
                        deadline=deadline, on_chunk=on_chunk)
                ok = True
            except NodeAgent._ObjectGone:
                return False
            finally:
                if not ok:
                    # The partial marker drops BEFORE the buffer's
                    # memory can be reused: a peer's fetch_chunk must
                    # never copy out of an aborted arena region.
                    self._partial.pop(oid, None)
                buf.release()
                if not ok:
                    # Covers gone, transfer errors and cancellation: never
                    # leave a permanently-unsealed object wedging this id
                    # — and never seal a partially-filled buffer (the
                    # caller withdraws the directory registration too).
                    self.store.abort(oid)
            self.store.seal(oid)
            self.store.release(oid)
            frec.recorder().instant("transfer", "commit", id=oid,
                                    bytes=size)
            # Sealed into the store before the partial record drops:
            # a peer's fetch_chunk always finds one of the two.
            self._partial.pop(oid, None)
            return True
        finally:
            self._pull_done()

    @staticmethod
    def _pwrite_chunk(path: str, data, pos: int) -> None:
        """Positional chunk write with its own fd: runs on an executor
        thread, and a stray write from a cancelled pull can never hit a
        recycled fd number (no shared-fd lifetime).  Loops on short
        writes — a silently partial chunk would later pread back as a
        zero-filled hole in a 'complete' spilled object."""
        view = memoryview(data)
        fd = os.open(path, os.O_WRONLY)
        try:
            off = 0
            while off < view.nbytes:
                n = os.pwrite(fd, view[off:], pos + off)
                if n <= 0:
                    raise IOError(
                        f"pwrite stalled at {off}/{view.nbytes} "
                        f"bytes of chunk @{pos} in {path}")
                off += n
        finally:
            os.close(fd)

    async def _pull_to_disk(self, peers, oid: bytes, size: int,
                            deadline: float | None = None,
                            owner=None) -> bool:
        path = self._spill_path(oid)
        # Create/truncate up front; chunk commits reopen positionally.
        os.close(os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644))
        loop = asyncio.get_running_loop()

        async def commit(pos, data):
            # Chunks stage in memory (window x chunk bytes, bounded) and
            # flush off-loop — a dirty-page writeback stall must not
            # freeze the agent's event loop.
            await loop.run_in_executor(
                None, self._pwrite_chunk, path, data, pos)

        # Disk-destined pulls don't serve partial chunks (the staged
        # buffers are transient), but the marker still answers peers
        # "later" instead of "gone" — a swarm member under memory
        # pressure must not push siblings toward lineage recovery.
        self._partial[oid] = {"size": size, "buf": None, "done": []}

        def on_chunk(pos, n):
            self._bytes_pulled += n

        ok = False
        try:
            try:
                await self._stream_chunks(
                    peers, oid, size,
                    make_sink=lambda pos, n: memoryview(bytearray(n)),
                    commit=commit, deadline=deadline, on_chunk=on_chunk)
                ok = True
            except NodeAgent._ObjectGone:
                return False
        finally:
            if not ok:
                self._partial.pop(oid, None)
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
        if not ok:
            return False
        self.spilled[oid] = (path, size)
        self._partial.pop(oid, None)
        # Non-primary disk copies are a bounded cache, LRU-evicted — the
        # owner's free only reaches the primary node (reference analogue:
        # remote copies are evictable, only primaries are pinned).
        self._disk_cached[oid] = size
        cap = self.store.stats()["capacity"]
        while sum(self._disk_cached.values()) > cap and len(self._disk_cached) > 1:
            old, osz = next(iter(self._disk_cached.items()))
            if old == oid:
                break
            self._disk_cached.pop(old)
            # Directory invalidation precedes the unlink: a puller
            # routed here between the two sees "gone" and fails over.
            self._drop_replica_registration(old)
            sp = self.spilled.pop(old, None)
            if sp is not None:
                try:
                    os.unlink(sp[0])
                except FileNotFoundError:
                    pass
        return True

    async def h_node_info(self, conn, p):
        return {
            "node_id": self.node_id,
            "address": list(self.address),
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "store_path": self.store_path,
            "num_workers": len(self.workers),
            "transfer": {"bytes_served": self._bytes_served,
                         "bytes_pulled": self._bytes_pulled},
        }

    async def h_store_stats(self, conn, p):
        st = self.store.stats()
        # Replica-plane observability: how wide the last pull's source
        # set was (tests assert a production pull sees >=2 once a
        # secondary exists) and cumulative transfer volume.
        st["last_pull_sources"] = self._last_pull_sources
        st["bytes_served"] = self._bytes_served
        st["bytes_pulled"] = self._bytes_pulled
        st["replica_registrations"] = len(self._replica_owner)
        return st

    async def h_list_objects(self, conn, p):
        """Full store index for the state API (reference: raylet
        GetObjectsInfo, node_manager.proto:521)."""
        return self.store.list_objects((p or {}).get("limit", 10_000))

    async def h_shutdown(self, conn, p):
        if (p or {}).get("graceful"):
            # Drain teardown: SIGTERM workers (their actor/lease conns
            # close, so clients fail over to restarted incarnations),
            # unlink the shm arena, then exit — same bounded discipline
            # as the SIGTERM path in _amain.
            async def _bye():
                try:
                    await asyncio.wait_for(self.close(), timeout=10)
                except Exception:
                    try:
                        os.unlink(self.store_path)
                    except OSError:
                        pass
                os._exit(0)

            rpc.spawn(_bye())
            return True
        asyncio.get_running_loop().call_later(0.05, lambda: os._exit(0))
        return True


async def _amain(args):
    rpc.enable_eager_tasks()
    set_config(Config(json.loads(args.system_config) if args.system_config else None))
    chaos_spec = get_config().rpc_chaos
    if chaos_spec:
        rpc.enable_chaos(chaos_spec)
    rpc.enable_link_chaos(get_config().link_chaos)
    rpc.enable_native_framer(get_config().rpc_native_framer)
    rpc.set_default_call_timeout(get_config().control_call_timeout_s)
    agent = NodeAgent(
        gcs_address=json.loads(args.gcs_address),
        session_dir=args.session_dir,
        node_id=bytes.fromhex(args.node_id),
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        store_capacity=args.store_capacity,
    )
    addr = await agent.start()
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"address": list(addr), "store_path": agent.store_path}, f)
        os.replace(tmp, args.ready_file)
    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    loop.add_signal_handler(signal.SIGTERM, stop.set)
    await stop.wait()
    # Bounded graceful drain, then hard exit: a SIGTERM'd agent must not
    # outlive its deadline because some peer keeps a connection open
    # (reference: raylet's graceful-shutdown deadline before _exit).
    try:
        await asyncio.wait_for(agent.close(), timeout=10)
    except Exception:
        # The arena unlink is close()'s last step — never skip it, or
        # repeated agent restarts leak /dev/shm until the tmpfs fills.
        try:
            os.unlink(agent.store_path)
        except OSError:
            pass
    from .node import dump_profile
    dump_profile()
    os._exit(0)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--store-capacity", type=int, default=1 << 30)
    parser.add_argument("--system-config", default="")
    parser.add_argument("--ready-file", default="")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()
    logging.basicConfig(level=args.log_level)
    from .node import install_daemon_profiler
    install_daemon_profiler("agent")
    from .auth import require_process_token
    require_process_token("agent", args.session_dir)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
