"""Shared build-on-first-use helper for the src/ native extensions.

One implementation of the pattern every ctypes binding used to copy
(shm_store, cgroup, rpcframe): rebuild the shared object with g++ when
it is missing or older than its source, under a caller-provided lock,
writing to a `.tmp<pid>` file and `os.replace`-ing into place so
concurrent processes race safely.  See src/README.md for the build
rules (flags, committed artifacts, degradation policy).
"""

from __future__ import annotations

import logging
import os
import subprocess

logger = logging.getLogger(__name__)

CXX_FLAGS = ["-O2", "-fPIC", "-shared", "-std=c++17"]


def build_so(src: str, so: str, ldflags: tuple = (),
             fallback_to_stale: bool = False) -> str:
    """Ensure `so` exists and is at least as new as `src`; returns the
    path.  With fallback_to_stale=True a failed rebuild (no compiler on
    this host, transient toolchain error) falls back to an EXISTING
    `so` instead of raising — for committed artifacts that remain
    loadable even when the checkout gave the source a newer mtime
    (loaders must gate on their own ABI check).  Callers serialize via
    their own module lock; this function only does the filesystem
    dance."""
    if os.path.exists(so) and (not os.path.exists(src)
                               or os.path.getmtime(so)
                               >= os.path.getmtime(src)):
        return so
    tmp = so + f".tmp{os.getpid()}"
    try:
        subprocess.run(["g++", *CXX_FLAGS, "-o", tmp, src, *ldflags],
                       check=True, capture_output=True)
        os.replace(tmp, so)
    except Exception as e:  # noqa: BLE001 — missing g++, compile error
        try:
            os.unlink(tmp)
        except OSError:
            pass
        if fallback_to_stale and os.path.exists(so):
            logger.warning(
                "rebuild of %s failed (%s: %s); using the existing "
                "artifact", so, type(e).__name__, e)
            return so
        raise
    return so
