"""JobSupervisor: detached actor driving one submitted job's entrypoint.

Reference surface: python/ray/dashboard/modules/job/job_supervisor.py:56 —
a per-job actor that execs the entrypoint command as a child process, tails
its output into a log file, and publishes status transitions. Job metadata
lives in the GCS KV under the "job_submission" namespace so it outlives the
supervisor (reference: JobInfoStorageClient over internal KV).
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import threading
import time
from typing import Dict, Optional

JOB_KV_NS = "job_submission"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _kv_put_info(core, submission_id: str, info: dict):
    core.gcs_call("kv_put", {"ns": JOB_KV_NS, "key": submission_id,
                             "value": json.dumps(info).encode(),
                             "overwrite": True})


def kv_get_info(core, submission_id: str) -> Optional[dict]:
    raw = core.gcs_call("kv_get", {"ns": JOB_KV_NS, "key": submission_id})
    return json.loads(bytes(raw)) if raw else None


class JobSupervisorImpl:
    """Runs inside a detached actor named JOB_SUPERVISOR_<id>."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Optional[Dict[str, str]] = None):
        import ray_tpu
        self._core = ray_tpu._core()
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_path = os.path.join(
            self._core.session_dir, "logs", f"job-{submission_id}.log")
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        self.info = {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": JobStatus.PENDING,
            "start_time": time.time(),
            "end_time": None,
            "message": "",
            "log_path": self.log_path,
        }
        _kv_put_info(self._core, submission_id, self.info)

        env = dict(os.environ)
        # The child driver joins THIS cluster instead of starting its own.
        gcs = self._core.gcs_address
        env["RAY_TPU_ADDRESS"] = f"{gcs[0]}:{gcs[1]}"
        env.update(env_vars or {})
        log_f = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            ["/bin/sh", "-c", entrypoint], stdout=log_f, stderr=log_f,
            env=env, cwd=os.getcwd(),   # = materialized working_dir, if any
            start_new_session=True)
        # The entrypoint runs in its own session so stop() can killpg it
        # without taking this worker down — which also detaches it from
        # normal teardown, so kill the group when this process exits.
        atexit.register(self._kill_child_group)
        self._set_status(JobStatus.RUNNING)
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _kill_child_group(self):
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def _set_status(self, status: str, message: str = ""):
        self.info["status"] = status
        self.info["message"] = message
        if status in JobStatus.TERMINAL:
            self.info["end_time"] = time.time()
        _kv_put_info(self._core, self.submission_id, self.info)

    def _wait(self):
        rc = self.proc.wait()
        if self.info["status"] != JobStatus.STOPPED:
            if rc == 0:
                self._set_status(JobStatus.SUCCEEDED)
            else:
                self._set_status(JobStatus.FAILED,
                                 f"entrypoint exited with code {rc}")
        # The supervisor's work is done: exit after a log-serving grace
        # window so it doesn't hold a worker process + CPU slice forever
        # (clients fall back to the KV record + log file afterwards).
        threading.Thread(target=self._retire, daemon=True).start()

    def _retire(self, grace_s: float = 120.0):
        time.sleep(grace_s)
        try:
            import ray_tpu
            core = ray_tpu._core()
            core.kill_actor(core.current_actor_id, no_restart=True)
        except Exception:
            os._exit(0)

    # ------------------------------------------------------------- actor API -
    def status(self) -> dict:
        return dict(self.info)

    def logs(self, offset: int = 0, max_bytes: int = 4 << 20) -> bytes:
        """Log content from byte `offset` (tail streaming reads
        incrementally; offset 0 + large max_bytes = whole log)."""
        try:
            with open(self.log_path, "rb") as f:
                f.seek(offset)
                return f.read(max_bytes)
        except FileNotFoundError:
            return b""

    def stop(self) -> bool:
        """SIGTERM the entrypoint's process group; SIGKILL after a grace
        period (reference: JobSupervisor.stop)."""
        if self.info["status"] in JobStatus.TERMINAL:
            return False
        self._set_status(JobStatus.STOPPED, "stopped by user")
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            return True

        def _escalate():
            time.sleep(5)
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except ProcessLookupError:
                pass
        threading.Thread(target=_escalate, daemon=True).start()
        return True

    def ping(self) -> str:
        return "pong"
