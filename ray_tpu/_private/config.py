"""Runtime config/flag registry.

TPU-native equivalent of the reference's RAY_CONFIG X-macro registry
(reference: src/ray/common/ray_config_def.h — 234 entries, each overridable by
an `RAY_<name>` env var and cluster-wide via the `_system_config` JSON passed
to init). Here every flag is declared once with a typed default, overridable by
`RAY_TPU_<name>` in the process environment and by the `_system_config` dict
passed to `ray_tpu.init`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

_REGISTRY: Dict[str, tuple] = {}


def _define(name: str, default: Any, doc: str = ""):
    _REGISTRY[name] = (type(default), default, doc)


# ---- core runtime -----------------------------------------------------------
_define("object_store_memory_bytes", 0, "0 = auto (30% of system RAM, capped)")
_define("object_store_auto_fraction", 0.3)
_define("object_store_max_auto_bytes", 16 * 1024**3)
_define("object_store_table_slots", 1 << 16)
_define("max_direct_call_object_size", 100 * 1024,
        "results <= this are returned inline to the owner's memory store "
        "(reference: RAY_CONFIG max_direct_call_object_size, 100KB)")
_define("memory_store_max_bytes", 512 * 1024 * 1024)
_define("worker_register_timeout_s", 60.0)
_define("cgroup_enabled", False,
        "place worker processes in a cgroup v2 group (reference: "
        "common/cgroup2 system/application split); no-op when cgroup2 "
        "is unavailable or read-only")
_define("cgroup_memory_max_bytes", 0, "0 = no kernel memory cap")
_define("memory_usage_threshold", 0.95,
        "node memory fraction above which the agent's memory monitor kills "
        "a worker (reference: RAY_memory_usage_threshold); >=1 disables")
_define("memory_monitor_refresh_ms", 250,
        "memory monitor poll period (reference: "
        "RAY_memory_monitor_refresh_ms); 0 disables the monitor")
_define("worker_lease_timeout_s", 30.0)
_define("num_workers_soft_limit", 0, "0 = num_cpus")
_define("max_leases_per_scheduling_key", 64,
        "client-side cap on concurrent worker leases per scheduling key "
        "(reference: normal_task_submitter lease pool; queue-bounded anyway)")
_define("max_tasks_in_flight_per_worker", 64,
        "ceiling of the ADAPTIVE per-lease submit window: the pipeline "
        "deepens toward this while observed push->complete latency stays "
        "low and shrinks back on backpressure/loss (reference: "
        "normal_task_submitter.cc max_tasks_in_flight_per_worker)")
_define("submit_batch_ack_timeout_s", 15.0,
        "how long a submitter waits for a submit_batch enqueue-ack before "
        "resending the still-unfinished tasks (the worker dedups by task "
        "id, so a dropped ack is harmless); 4 lost acks recycle the "
        "connection")
_define("worker_pythonpath_strip_cpu", ".axon_site",
        "PYTHONPATH entries containing this substring are stripped from "
        "CPU-only workers so accelerator site hooks (eager TPU client "
        "init) don't slow spawn or grab chip state; empty disables")
_define("worker_prestart_count", 2,
        "workers spawned at agent boot so first leases don't pay process "
        "startup (reference: worker_pool.cc prestart)")
_define("worker_fork_server", True,
        "fork default-env CPU workers from a warm pre-imported zygote "
        "process (~100ms) instead of exec+reimport (~seconds); TPU and "
        "runtime-env workers always exec fresh (zygote.py)")
_define("worker_niceness", 0)
_define("maximum_gcs_destroyed_actor_cached_count", 100_000)
_define("task_max_retries_default", 3)
_define("actor_max_restarts_default", 0)
_define("actor_scheduling_timeout_s", 90.0,
        "how long a pending actor waits for a feasible node before failing; "
        "the deadline restarts whenever a new node registers, so autoscaler "
        "provisioning slower than this does not kill pending actors "
        "(reference: GcsActorScheduler queues indefinitely)")
_define("health_check_period_ms", 1000,
        "reference: gcs_health_check_manager.h health_check_period_ms")
_define("health_check_failure_threshold", 5)
_define("gcs_lease_ttl_s", 3.0,
        "primary GCS advertised-address lease TTL: the primary renews "
        "the on-disk lease every ttl/3 while it holds agent-heartbeat "
        "majority; a warm standby takes over (bumping the cluster "
        "epoch) only after the lease has been stale for a full TTL")
_define("gcs_standby_poll_ms", 100,
        "warm-standby journal-tail and lease-check poll interval")
_define("gcs_lease_heartbeat_fresh_s", 0.0,
        "heartbeat freshness window for the lease-renewal majority "
        "condition; 0 = auto (4x resource_report_period_ms, min 2s). "
        "A primary that cannot see fresh heartbeats from a majority of "
        "alive agents stops renewing — so a primary partitioned from "
        "the cluster yields, while a standby partitioned from a "
        "healthy primary never steals the lease (split-brain guard)")
_define("journal_snapshot_every_bytes", 64 * 1024 * 1024,
        "GCS journal compaction threshold: when the journal file "
        "exceeds this many bytes, the primary writes a full-table "
        "snapshot record to a fresh file and atomically replaces the "
        "journal (replay = snapshot + suffix); 0 disables compaction. "
        "Standby tailers detect the replacement by inode change and "
        "re-read from the snapshot")
_define("resource_report_period_ms", 250,
        "ray_syncer-equivalent periodic resource view broadcast")
_define("lineage_max_entries", 100_000,
        "owner-side lineage cap (reference: task_manager.h max_lineage_bytes)")
_define("object_spill_dir", "", "empty = <session_dir>/spill")
_define("object_spill_external_uri", "",
        "external/cloud spill tier (reference: _private/external_storage"
        ".py:398 smart_open impl): when set (file:///shared/mount, "
        "mock://bucket/prefix, or a registered custom scheme), every "
        "local spill also uploads a durable copy and registers its URI "
        "in the GCS KV, so any node can restore a dead node's spilled "
        "objects without lineage re-execution")
_define("object_spill_threshold", 0.8,
        "fraction of store capacity above which pinned primaries spill "
        "proactively (reference: local_object_manager.h spill threshold)")
_define("object_transfer_chunk_bytes", 8 * 1024 * 1024,
        "inter-node object transfer chunk size "
        "(reference: object_manager chunked push, default 5MiB chunks)")
_define("max_concurrent_pulls", 16,
        "per-node cap on simultaneous inbound object pulls "
        "(reference: pull_manager.cc bundle admission)")
_define("object_transfer_max_inflight_chunks", 8,
        "chunk requests kept in flight per object pull — pipelines the "
        "source's shm read / spill-file read under the wire transfer "
        "(reference: object_manager.cc overlapping chunked push)")
_define("object_transfer_chunk_timeout_s", 30.0,
        "per-chunk fetch deadline during a pull; an expired chunk retries "
        "on the same source then fails over to alternates")
_define("task_arg_fetch_timeout_s", 600.0,
        "bound on an executing task's by-reference arg fetch; a freed or "
        "unrecoverable arg fails the task instead of wedging the worker")
_define("create_backpressure_timeout_s", 30.0,
        "how long a plasma put waits for spill/eviction to make room before "
        "failing (reference: plasma create_request_queue semantics)")
_define("create_queue_depth", 32,
        "bound on the agent's FIFO create-admission queue (the "
        "CreateRequestQueue analogue): puts/seals that cannot reserve "
        "arena headroom park here while eviction/spill makes room; a "
        "full queue or an expired deadline fails the create TYPED as "
        "ObjectStoreFullError(retry_after_s) — never a raw arena "
        "exception (reference: plasma create_request_queue.h)")
_define("eviction_pinned_bytes_floor", 0,
        "pressure sweeps never spill arena-resident pinned primaries "
        "below this many bytes (0 = no floor): keeps a hot working set "
        "resident even under admission pressure; re-fetchable "
        "secondaries are always dropped first regardless of the floor")
_define("lease_shed_pressure_threshold", 0.95,
        "when the node's shared memory-pressure signal (max of arena "
        "occupancy, node RAM, KV pool, chaos squeeze) is at or above "
        "this fraction, lease granting prefers spilling tasks back to a "
        "feasible peer over granting locally — memory_monitor feeds the "
        "same signal the create queue drains.  Only sheds when a "
        "spillback target exists; a sole node always grants")
_define("kv_cache_demotion_enabled", True,
        "LRU-evicted prefix-cache pages demote into host/NVMe KV parts "
        "(the external-KV part format) instead of being freed; a later "
        "prefix hit promotes them back via direct re-install + "
        "device_put, so cache hit rate survives page-pool pressure")
_define("kv_demoted_bytes_limit", 256 * 1024 * 1024,
        "byte bound on the demoted prefix-cache tier; the host window "
        "holds the hot tail and overflows to NVMe files under the spill "
        "dir, oldest demoted entries are dropped past the bound")
_define("mem_chaos", "",
        "memory-pressure chaos: 'arena=frac:period_s[,pool=frac]' — "
        "squeeze the EFFECTIVE arena budget to frac of capacity (and "
        "optionally the KV page pool to pool-frac) during alternate "
        "half-periods, then restore it; drives spill/eviction/"
        "backpressure and KV demotion under load, composing with "
        "process/link chaos (empty = off)")
_define("rpc_connect_retries", 10)
_define("rpc_connect_retry_delay_s", 0.2)
_define("rpc_native_framer", True,
        "run RPC wire framing through the _rpcframe.so C extension "
        "(src/rpcframe): C stream scanner + raw chunks recv'd straight "
        "into the shm arena + vectored writev frame waves (reference: "
        "Ray keeps its whole rpc/object-transfer plane in C++, "
        "src/ray/rpc + object_manager).  Per process/node; the wire "
        "format is identical to the pure-Python framer, so clusters may "
        "mix modes freely.  Off, a missing compiler, or a corrupt .so "
        "all fall back to pure Python (warn once, never an error)")
_define("daemon_io_shards", -1,
        "I/O shards for the daemon RPC planes (GCS and node agents): "
        "accepted connections are distributed round-robin across this "
        "many per-shard event-loop THREADS, each running the full wire "
        "path (framing, msgpack codec, native-framer recv/writev) for "
        "its connections; handlers that only touch the arena/io run "
        "entirely on their shard, state-mutating handlers hop to the "
        "daemon's main loop in ONE batched call_soon_threadsafe per "
        "ready-wave.  -1 = auto (min(4, cpu cores)); 0 = single-loop "
        "mode (everything on the main loop, exactly the pre-shard "
        "behavior).  The wire format is identical in both modes, so "
        "clusters may mix sharded and unsharded daemons freely "
        "(reference: Ray's GCS and raylet run their gRPC services on "
        "dedicated C++ executor thread pools)")
_define("control_call_timeout_s", 60.0,
        "default deadline for unary control-plane RPCs whose call site "
        "passes no timeout: a half-open connection (gray peer, asymmetric "
        "partition) can then never hang a caller forever.  Streaming-ish "
        "calls that legitimately block (actor pushes, stream "
        "backpressure, object long-polls) opt out with explicit "
        "timeout=0; 0 here disables the default entirely")
_define("replica_directory_enabled", True,
        "owners track EVERY holder of a plasma object (primary + pulled "
        "secondaries, reference: the ownership table tracks all object "
        "locations, Ownership NSDI'21): pulls stamp the full from_addrs "
        "set (hedging/failover get real alternates), concurrent pulls "
        "stripe chunks across holders (Cornet-style swarm broadcast), "
        "and the scheduler scores bytes-already-local placement")
_define("replica_directory_max_secondaries", 8,
        "per-object cap on tracked secondary holders (oldest registration "
        "dropped first; secondaries are evictable caches, so a dropped "
        "entry only costs the swarm a source)")
_define("object_locality_scheduling_enabled", True,
        "score default-strategy placement by bytes already local to each "
        "candidate node (task-spec ref-arg location hints); never "
        "overrides feasibility, labels, or trusted-first ordering")
_define("object_locality_min_bytes", 1024 * 1024,
        "ignore locality below this many hinted arg bytes — tiny args "
        "re-fetch faster than a misplaced lease costs")
_define("arg_prefetch_enabled", True,
        "on lease grant the agent immediately starts pulling the lease's "
        "missing large by-reference args, overlapping the fetch with "
        "worker dispatch/queueing (reference: the raylet pulls task args "
        "during lease setup, pull_manager task-arg bundles)")
_define("arg_prefetch_min_bytes", 1024 * 1024,
        "only prefetch args at least this large: small args resolve "
        "through the owner faster than a pull round-trip")
_define("pull_hedge_enabled", True,
        "race a backup source for a pull chunk once the primary exceeds "
        "its observed p95 latency (Dean & Barroso hedged requests); "
        "needs >=2 sources (from_addrs) to engage")
_define("pull_hedge_delay_ms", 0,
        "hedge delay override; 0 = adaptive (per-peer p95 of recent "
        "chunk fetches, 200ms until enough samples)")
_define("pull_hedge_budget_fraction", 0.1,
        "cap on hedged fetches as a fraction of total chunk fetches "
        "(plus a small burst) so hedging cannot amplify load on an "
        "already-throttled cluster")
_define("gray_bulk_drain_exempt_bytes_per_s", 8 * 1024 * 1024,
        "hold the gray AUTO-DRAIN (placement deprioritization still "
        "applies) while a suspect node is moving at least this much "
        "object-plane data per second between heartbeats — a node "
        "serving a weight broadcast is busy, not gray, and evacuating "
        "it would kill the transfer that inflated its probe RTT; 0 "
        "disables the exemption")
_define("gray_suspicion_threshold", 0.6,
        "per-node suspicion score (0..1, EMA of RTT-vs-cluster-baseline "
        "and heartbeat-staleness evidence) above which a node is "
        "treated as gray-suspect: placement deprioritizes it and, "
        "sustained, it is auto-drained")
_define("gray_sustained_s", 5.0,
        "how long suspicion must stay above the threshold before the "
        "GCS auto-drains the node with reason='gray' (0 disables the "
        "sustain requirement, not the drain)")
_define("gray_auto_drain", True,
        "auto-trigger drain_node(reason='gray') for a sustained-suspect "
        "node (detect -> avoid -> evacuate); never drains the last "
        "healthy node")
_define("gray_min_rtt_ms", 100.0,
        "absolute RTT floor below which a node is never gray-suspect "
        "(pure ratio-to-baseline would flag healthy microsecond-RTT "
        "nodes on an idle cluster)")
_define("gray_rtt_ratio", 3.0,
        "probe/peer RTT must also exceed this multiple of the cluster "
        "median RTT to count as gray evidence")
_define("rpc_chaos", "",
        "deterministic RPC fault injection: 'Method=N:req%:resp%' "
        "(reference: src/ray/rpc/rpc_chaos.cc RAY_testing_rpc_failure)")
_define("link_chaos", "",
        "deterministic link-level fault injection on the RPC byte "
        "stream: '[match/]kind=fields,...' with kind in out_delay|"
        "in_delay|out_bw|in_bw|out_drop|in_drop — per-peer delay+jitter, "
        "bandwidth throttling, and ASYMMETRIC partitions (out_drop "
        "blackholes A->B while B->A flows); enabling it process-wide on "
        "one node is slow-node mode (_private/chaos.py LinkChaos)")
_define("process_chaos", "",
        "deterministic process-kill fault injection for cluster fixtures: "
        "'class=N:period_s[:delay_s]' with class in worker|agent|gcs — "
        "SIGKILLs N processes of that class, one every period_s seconds "
        "(first after delay_s); mirrors the rpc_chaos spec style but "
        "exercises the CRASH paths message drops never reach "
        "(_private/chaos.py ProcessChaos)")
_define("node_drain_deadline_s", 30.0,
        "default deadline for the two-phase graceful node drain "
        "(drain_node without an explicit deadline_s): the GCS stops "
        "scheduling onto the node, restarts its actors elsewhere and "
        "migrates sole primary object copies off it, then falls back to "
        "the hard-kill death path when the deadline expires "
        "(reference: autoscaler.proto DrainNode deadline_timestamp_ms)")
_define("grant_or_reject_spillback", True)
_define("scheduler_top_k_fraction", 0.2,
        "hybrid policy: pick among best-k nodes "
        "(reference: hybrid_scheduling_policy.h)")
_define("scheduler_spread_threshold", 0.5,
        "node utilization below which hybrid policy packs "
        "(reference: RAY_scheduler_spread_threshold)")
_define("put_small_object_in_memory_store", True)
_define("metrics_report_interval_ms", 2000)
_define("event_buffer_max_events", 10_000)
_define("task_event_flush_interval_s", 1.0,
        "task-event + metric buffer flush period "
        "(reference: task_event_buffer.h report interval)")
_define("gcs_task_events_max", 100_000,
        "GCS-side ring buffer cap on retained task events "
        "(reference: RAY_task_events_max_num_task_in_gcs)")
_define("log_rotation_bytes", 100 * 1024 * 1024)

# ---- observability: flight recorder + clock alignment -----------------------
_define("flight_recorder_enabled", True,
        "per-process flight recorder: preallocated ring of plane-level "
        "events (lease lifecycle, object-transfer timelines) flushed "
        "over the existing heartbeat/telemetry batching — no new "
        "per-event RPCs (reference: Ray's task_event_buffer + "
        "src/ray/util/event.h bounded in-memory event rings)")
_define("flight_recorder_capacity", 4096,
        "flight-recorder ring slots per process; overflow drops the "
        "OLDEST record and counts it (exported as "
        "ray_tpu_flight_recorder_dropped_total)")
_define("flight_recorder_categories", "",
        "comma-separated category gate for the flight recorder "
        "(lease,transfer,sched,request,anomaly); empty = all "
        "categories on")
_define("flight_recorder_sample_n", 1,
        "record 1 of every N instant events per category (spans are "
        "never sampled away); 1 = record everything")
_define("clock_align_enabled", True,
        "estimate per-node wall-clock offsets from the GCS health-loop "
        "RTT probes (NTP-style theta = ((t1-t0)+(t2-t3))/2, min-RTT "
        "filtered + smoothed), stamp them into node views, and apply "
        "them in timeline rendering so cross-node spans nest correctly")
_define("clock_skew_s", 0.0,
        "CHAOS: shift this process's telemetry wall clock by this many "
        "seconds (clocks.wall()).  Set per node via _system_config to "
        "fake disagreeing host clocks; the agent forwards it to its "
        "workers' env so the whole node skews coherently")
_define("metrics_export_enabled", True,
        "ship each daemon's util.metrics registry + runtime gauges "
        "(arena occupancy, lease queue depth, io_stats, copy-audit, "
        "recorder drops) to the GCS on its heartbeat/telemetry tick; "
        "the dashboard /metrics exposition then carries node_id-labeled "
        "series for every node")

# ---- observability: diagnosis plane (watchdogs + black-box capture) ---------
_define("diagnosis_enabled", True,
        "run the per-daemon hung-work watchdogs (wedged event loops, "
        "tasks RUNNING past their historical p95, leases "
        "granted-but-never-RUNNING, serving requests "
        "admitted-but-token-silent); each detector emits a typed "
        "`anomaly` flight-recorder event and a ray_tpu_anomaly_total "
        "counter (reference spirit: Google-Wide Profiling / Tail at "
        "Scale always-on anomaly capture)")
_define("diagnosis_poll_ms", 500,
        "watchdog thread poll period; detectors are O(tracked work) "
        "dict scans, so this bounds detection latency, not overhead")
_define("diagnosis_loop_wedge_s", 5.0,
        "a loopmon entry stale at least this long while its thread is "
        "still alive is a WEDGED loop (not a stopped one) -> dump its "
        "stack via sys._current_frames from the watchdog thread")
_define("diagnosis_task_hang_multiple", 20.0,
        "a task RUNNING longer than this multiple of its function's "
        "historical p95 (EMA over completed runs) is flagged hung")
_define("diagnosis_task_hang_min_s", 10.0,
        "floor on the per-function hang threshold so short functions "
        "with microsecond p95s don't flap")
_define("diagnosis_task_hang_default_s", 120.0,
        "hang threshold for functions with no completion history yet")
_define("diagnosis_lease_stall_s", 15.0,
        "a lease granted this long ago whose worker has started zero "
        "tasks since the grant (and runs none now) is a stalled lease "
        "-> the owner likely wedged or the push never arrived")
_define("diagnosis_serving_silence_s", 15.0,
        "a serving request admitted into a decode batch but token-silent "
        "this long is flagged (decode loop wedged or request starved)")
_define("anomaly_capture_enabled", True,
        "when a detector fires, the GCS snapshots the implicated nodes "
        "(recorder drain, stacks, CPU profile, metrics, node views) "
        "into a diag-<kind>-<ts>/ black-box bundle")
_define("diagnosis_capture_dir", "",
        "bundle output directory; empty = <session_dir>/diagnosis")
_define("diagnosis_capture_min_interval_s", 60.0,
        "per-anomaly-kind rate limit on bundle capture: a flapping "
        "detector keeps counting but cannot DoS the cluster with "
        "bundle I/O inside this window")
_define("diagnosis_capture_profile_s", 2.0,
        "CPU-profile sampling window captured into each bundle")
_define("diagnosis_capture_max_bundles", 20,
        "oldest bundles are pruned beyond this many (disk bound)")
_define("diagnosis_chaos_enabled", False,
        "CHAOS: expose debug handlers that wedge daemon loops on "
        "purpose (tests only; never enable in production)")

# ---- TPU specifics ----------------------------------------------------------
_define("tpu_chips_per_host_default", 4)
_define("tpu_visible_chips_env", "TPU_VISIBLE_CHIPS")
_define("jax_platforms_for_workers", "", "empty = inherit")
_define("mesh_default_axis_names", "dp,fsdp,tp")


class Config:
    """Resolved config: defaults < env (RAY_TPU_<name>) < _system_config."""

    def __init__(self, system_config: Dict[str, Any] | None = None):
        self._values: Dict[str, Any] = {}
        for name, (typ, default, _doc) in _REGISTRY.items():
            val = default
            env = os.environ.get(f"RAY_TPU_{name}")
            if env is not None:
                val = _parse(typ, env)
            self._values[name] = val
        for k, v in (system_config or {}).items():
            if k not in _REGISTRY:
                raise ValueError(f"unknown _system_config key: {k}")
            self._values[k] = v

    def __getattr__(self, name):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Config":
        cfg = cls.__new__(cls)
        cfg._values = dict(d)
        return cfg


def _parse(typ, s: str):
    if typ is bool:
        return s.lower() in ("1", "true", "yes")
    if typ in (int, float):
        return typ(s)
    if typ in (dict, list):
        return json.loads(s)
    return s


def resolve_io_shards(cfg: "Config" | None = None) -> int:
    """Effective daemon I/O shard count: the configured value, with -1
    (auto) resolving to min(4, cpu cores).  0 disables sharding."""
    cfg = cfg or get_config()
    n = int(cfg.daemon_io_shards)
    if n < 0:
        n = min(4, os.cpu_count() or 1)
    return max(0, n)


_global: Config | None = None


def get_config() -> Config:
    global _global
    if _global is None:
        _global = Config()
    return _global


def set_config(cfg: Config):
    global _global
    _global = cfg
