"""Device-direct data plane: first-class device-array payloads.

Reference surface: Ray's RDT/GPU-object transport (`tensor_transport`
actor option + the transport manager keyed on object refs) and aDAG's
pluggable accelerator channels (`TorchTensorAcceleratorChannel` /
`AcceleratorContext`).  TPU-native design: a `DeviceArraySpec` payload
type negotiated at DAG compile time plus a transport ladder —

  rung 0 (same process / same slice): the producer registers the live
      jax.Arrays in an in-process table and ships only an 8-byte token +
      spec over the ring slot ("ring slots carry specs, not blobs");
      the consumer takes the very same arrays.  ZERO host bytes.
  rung 1 (cross-process): the serializer stages each array exactly once
      — a dlpack/`__array_interface__` host view travels as a pickle-5
      out-of-band buffer straight into the arena `create_buffer` view
      (no intermediate `np.asarray` materialization, no pickle of the
      payload bytes), ships over the native framer, and is re-uploaded
      with `jax.device_put` on the far side.  ONE host copy per
      direction, test-pinned by the copy audit below.

Copy audit: `device_to_host_bytes` / `host_to_device_bytes` are stamped
at every transfer seam and exported through the unified metrics registry
(`ray_tpu_device_to_host_bytes_total` / `ray_tpu_host_to_device_bytes_total`)
so "zero host-staging bytes on same-slice edges" is a pinned invariant,
not a claim.  `device_fallback_bytes` counts arrays that could not
export a zero-copy host view (non-contiguous shardings, real
accelerators without a host-addressable buffer) and paid the extra
materialization — report-only, never an error.
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

# -- copy audit ---------------------------------------------------------------

_audit_lock = threading.Lock()
_audit = {
    "device_to_host_bytes": 0,   # staging copies: device buffer -> host view
    "host_to_device_bytes": 0,   # uploads: host view -> device buffer
    "device_fallback_bytes": 0,  # subset of d2h that paid an EXTRA copy
    "device_arrays_staged": 0,
    "device_arrays_local": 0,    # rung-0 handoffs (no bytes moved)
}


def _record(key: str, nbytes: int, count_key: Optional[str] = None):
    with _audit_lock:
        _audit[key] += nbytes
        if count_key:
            _audit[count_key] += 1
    try:
        from ray_tpu.util.metrics import Counter
        if key == "device_to_host_bytes":
            Counter("ray_tpu_device_to_host_bytes_total",
                    "device->host staging bytes (copy audit)").inc(nbytes)
        elif key == "host_to_device_bytes":
            Counter("ray_tpu_host_to_device_bytes_total",
                    "host->device upload bytes (copy audit)").inc(nbytes)
        elif key == "device_fallback_bytes":
            Counter("ray_tpu_device_staging_fallback_bytes_total",
                    "device staging bytes that paid an extra "
                    "materialization (non-contiguous / unaddressable)"
                    ).inc(nbytes)
    except Exception:
        pass  # metrics registry must never break the data path


def device_copy_stats() -> dict:
    """Snapshot of the device copy-audit counters for this process."""
    with _audit_lock:
        return dict(_audit)


def record_d2h(nbytes: int) -> None:
    """Audit a device->host copy made OUTSIDE the serializer (explicit
    host-staging downgrades, e.g. the engine's host-staged KV A/B path):
    every transfer seam counts, not just the automatic ones."""
    _record("device_to_host_bytes", int(nbytes))


def record_h2d(nbytes: int) -> None:
    """Audit a host->device upload made outside the serializer."""
    _record("host_to_device_bytes", int(nbytes))


def _reset_copy_stats():
    """Test helper: zero the audit so deltas can be asserted exactly."""
    with _audit_lock:
        for k in _audit:
            _audit[k] = 0


# -- jax detection ------------------------------------------------------------

def _jax():
    """The jax module IF the process already imported it (a value can only
    contain jax.Arrays if jax is loaded; never import it ourselves)."""
    return sys.modules.get("jax")


def is_device_array(x) -> bool:
    jax = _jax()
    if jax is None:
        return False
    try:
        return isinstance(x, jax.Array) and not isinstance(
            x, jax.core.Tracer)
    except Exception:
        return False


# -- specs --------------------------------------------------------------------

@dataclass(frozen=True)
class DeviceArraySpec:
    """Compile-time-negotiable description of a device-array payload:
    what crosses a DAG edge is this spec; the bytes ride the ladder."""
    dtype: str
    shape: Tuple[int, ...]
    nbytes: int
    sharding: str  # fingerprint: platform + participating-device count

    @classmethod
    def of(cls, arr) -> "DeviceArraySpec":
        try:
            devs = arr.sharding.device_set
            plat = next(iter(devs)).platform if devs else "?"
            fp = f"{plat}:{len(devs)}"
        except Exception:
            fp = "host:1"
        return cls(dtype=str(arr.dtype), shape=tuple(arr.shape),
                   nbytes=int(arr.nbytes), sharding=fp)

    def compatible(self, other: "DeviceArraySpec") -> bool:
        return (self.dtype == other.dtype and self.shape == other.shape)


def spec_of(x) -> Optional[DeviceArraySpec]:
    return DeviceArraySpec.of(x) if is_device_array(x) else None


def validate_against_spec(value, spec: dict, where: str = "?"):
    """Step-time guard for a stage's DECLARED output spec: every device
    leaf of `value` must match the promised shape/dtype.  (Declaration
    disagreements between stages fail earlier, at compile time.)"""
    from ray_tpu import exceptions as exc
    want_shape = tuple(spec["shape"])
    want_dtype = spec["dtype"]

    def check(arr):
        if tuple(arr.shape) != want_shape or str(arr.dtype) != want_dtype:
            raise exc.DeviceSpecMismatchError(
                f"stage {where!r} produced a device array of "
                f"shape={tuple(arr.shape)} dtype={arr.dtype}, but its "
                f"declared payload spec is shape={want_shape} "
                f"dtype={want_dtype}")
        return arr

    _map_device_leaves(value, check)


# -- host staging (rung 1) ----------------------------------------------------

def _host_view(arr) -> Tuple[Any, bool]:
    """A host-memory ndarray exposing `arr`'s bytes.  Returns
    (view, zero_copy): zero_copy=True means the view ALIASES the device
    buffer (CPU backend / host-addressable memory — the arena memcpy is
    then the only copy); False means we had to materialize (counted as
    `device_fallback_bytes`)."""
    import numpy as np
    try:
        if len(arr.sharding.device_set) == 1 and arr.is_fully_addressable:
            v = np.from_dlpack(arr)
            if v.flags["C_CONTIGUOUS"]:
                return v, True
    except Exception:
        pass
    return np.asarray(arr), False


class _DeviceLeaf:
    """Serialize-side wrapper substituted for a jax.Array leaf: pickles
    as (spec, PickleBuffer over a host view) so the payload bytes travel
    out-of-band and land in the arena with exactly one memcpy."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr

    def __reduce_ex__(self, protocol):
        arr = self.arr
        view, zero_copy = _host_view(arr)
        nbytes = int(view.nbytes)
        _record("device_to_host_bytes", nbytes, "device_arrays_staged")
        if not zero_copy:
            _record("device_fallback_bytes", nbytes)
        spec = DeviceArraySpec.of(arr)
        return (_rebuild_device_array,
                (spec, pickle.PickleBuffer(view)))


def _rebuild_device_array(spec: DeviceArraySpec, buf):
    """Deserialize-side reconstructor: one `jax.device_put` upload from
    the (possibly arena-backed) buffer; no intermediate host copy."""
    import numpy as np
    jax = sys.modules.get("jax")
    arr = np.frombuffer(buf, dtype=np.dtype(spec.dtype)).reshape(spec.shape)
    _record("host_to_device_bytes", int(arr.nbytes))
    _notice_rebuilt(int(arr.nbytes))
    if jax is None:
        import jax  # the consumer needs a device to land on
    return jax.device_put(arr)


# -- container walking --------------------------------------------------------

_MAX_DEPTH = 8


def _map_device_leaves(value, fn: Callable, depth: int = _MAX_DEPTH):
    """Rebuild `value` with every jax.Array leaf replaced by fn(leaf).
    Containers (list/tuple/dict) are walked to a bounded depth; other
    objects pass through untouched (a custom object hiding a device
    array falls back to jax's own pickle path).  Returns (new, hits)."""
    if is_device_array(value):
        return fn(value), 1
    if depth <= 0:
        return value, 0
    if type(value) is list:
        hits, out = 0, []
        for v in value:
            nv, h = _map_device_leaves(v, fn, depth - 1)
            out.append(nv)
            hits += h
        return (out if hits else value), hits
    if type(value) is tuple:
        hits, out = 0, []
        for v in value:
            nv, h = _map_device_leaves(v, fn, depth - 1)
            out.append(nv)
            hits += h
        return (tuple(out) if hits else value), hits
    if type(value) is dict:
        hits, out = 0, {}
        for k, v in value.items():
            nv, h = _map_device_leaves(v, fn, depth - 1)
            out[k] = nv
            hits += h
        return (out if hits else value), hits
    return value, 0


def has_device_leaves(value) -> bool:
    if _jax() is None:
        return False
    _, hits = _map_device_leaves(value, lambda a: a)
    return hits > 0


def swap_device_leaves(value) -> Tuple[Any, int]:
    """Serializer pre-pass: substitute `_DeviceLeaf` wrappers so device
    bytes travel out-of-band (one copy).  Returns (value', n_leaves)."""
    if _jax() is None:
        return value, 0
    return _map_device_leaves(value, _DeviceLeaf)


def split_device_leaves(value):
    """Rung-0 encode: extract the live arrays.  Returns
    (skeleton, leaves, specs) where skeleton has `_LeafRef(i)` markers."""
    leaves: List[Any] = []
    specs: List[DeviceArraySpec] = []

    def grab(arr):
        leaves.append(arr)
        specs.append(DeviceArraySpec.of(arr))
        return _LeafRef(len(leaves) - 1)

    skeleton, _ = _map_device_leaves(value, grab)
    return skeleton, leaves, specs


@dataclass(frozen=True)
class _LeafRef:
    """Placeholder for a device leaf travelling out of band (rung 0)."""
    index: int


def join_device_leaves(skeleton, leaves):
    def back(v, depth=_MAX_DEPTH):
        if isinstance(v, _LeafRef):
            return leaves[v.index]
        if depth <= 0:
            return v
        if type(v) is list:
            return [back(x, depth - 1) for x in v]
        if type(v) is tuple:
            return tuple(back(x, depth - 1) for x in v)
        if type(v) is dict:
            return {k: back(x, depth - 1) for k, x in v.items()}
        return v
    return back(skeleton)


# -- deserialize-from-view safety --------------------------------------------

def detach_host_leaves(value, source: memoryview):
    """After deserializing DIRECTLY from an arena view (so device leaves
    upload straight from the arena), any host ndarray/bytes leaves still
    alias the view; copy them out so the arena pin can be released.
    Device payload skeletons carry only small metadata, so this is
    cheap — the device bytes themselves never touch it."""
    import numpy as np
    base = np.frombuffer(source, np.uint8)
    lo = base.ctypes.data
    hi = lo + base.nbytes

    def aliases(v) -> bool:
        b = v
        while b.base is not None and isinstance(b.base, np.ndarray):
            b = b.base
        try:
            ptr = b.__array_interface__["data"][0]
        except Exception:
            return False
        return lo <= ptr < hi

    def walk(v, depth=_MAX_DEPTH):
        if isinstance(v, np.ndarray):
            return v.copy() if aliases(v) else v
        if depth <= 0:
            return v
        if type(v) is list:
            return [walk(x, depth - 1) for x in v]
        if type(v) is tuple:
            return tuple(walk(x, depth - 1) for x in v)
        if type(v) is dict:
            return {k: walk(x, depth - 1) for k, x in v.items()}
        return v
    return walk(value)


# -- serialize/deserialize notices (TLS) -------------------------------------

_tls = threading.local()


def _notice_rebuilt(nbytes: int):
    _tls.rebuilt_bytes = getattr(_tls, "rebuilt_bytes", 0) + nbytes
    _tls.rebuilt_n = getattr(_tls, "rebuilt_n", 0) + 1


def take_rebuilt_notice() -> Tuple[int, int]:
    """(n_leaves, bytes) of device arrays rebuilt by THIS thread since
    the last call — lets get()/recv seams register device-tier locations
    without re-walking the value."""
    n = getattr(_tls, "rebuilt_n", 0)
    b = getattr(_tls, "rebuilt_bytes", 0)
    _tls.rebuilt_n = 0
    _tls.rebuilt_bytes = 0
    return n, b


def note_staged_leaves(n: int):
    _tls.staged_n = getattr(_tls, "staged_n", 0) + n


def take_staged_notice() -> int:
    n = getattr(_tls, "staged_n", 0)
    _tls.staged_n = 0
    return n


# -- rung-0 in-process registry ----------------------------------------------

MAGIC_LOCAL = b"\xffRTDVL\x00\x01"   # 8B: local-token device message
MAGIC_STAGED = b"\xffRTDVS\x00\x01"  # 8B: staged payload, in-place decode ok

_local_lock = threading.Lock()
_local: dict = {}        # token -> [leaves, remaining_takes]
_local_seq = [0]


def register_local(leaves: List[Any], nreaders: int) -> bytes:
    """Park live device arrays for same-process consumers; the ring
    carries only the returned 8-byte token.  Refcounted by reader."""
    import os
    with _local_lock:
        _local_seq[0] += 1
        token = struct.pack("<II", os.getpid() & 0xFFFFFFFF,
                            _local_seq[0] & 0xFFFFFFFF)
        _local[token] = [leaves, max(1, int(nreaders))]
    with _audit_lock:
        _audit["device_arrays_local"] += len(leaves)
    return token


def take_local(token: bytes) -> List[Any]:
    with _local_lock:
        ent = _local.get(token)
        if ent is None:
            raise KeyError(f"device-local token {token!r} not registered "
                           "(producer restarted or token already drained)")
        ent[1] -= 1
        if ent[1] <= 0:
            del _local[token]
        return ent[0]


def local_registry_size() -> int:
    with _local_lock:
        return len(_local)


def drop_local(token: bytes):
    """Unconditionally forget a token (producer-side cleanup on serve
    loop exit; missing tokens — already drained — are a no-op)."""
    with _local_lock:
        _local.pop(token, None)


def local_is_registered(token: bytes) -> bool:
    with _local_lock:
        return token in _local


# -- DAG body encode/decode ---------------------------------------------------

def dag_encode_body(ctx, status: bytes, value, local_ok: bool,
                    nreaders: int):
    """Build a DAG message body as a parts list ([status, ...]).

    rung 0 (local_ok, device leaves present): the ring carries
    MAGIC_LOCAL + (token, skeleton, specs) — the arrays never leave the
    device.  Returns (parts, token) so the producer can reclaim the
    registry entry if the pipeline tears down before consumers drain it.

    rung 1 (device leaves crossing processes): MAGIC_STAGED marks the
    payload as safe to decode IN PLACE from the arena view (device
    leaves upload straight from it; host leaves are detached).

    Plain host payloads keep the unmarked wire form."""
    if local_ok and has_device_leaves(value):
        skeleton, leaves, specs = split_device_leaves(value)
        token = register_local(leaves, nreaders)
        ser = ctx.serialize((token, skeleton,
                             [s.__dict__ for s in specs]))
        return [status, MAGIC_LOCAL, *ser], token
    take_staged_notice()                    # drain stale notices
    ser = ctx.serialize(value)
    if take_staged_notice():
        return [status, MAGIC_STAGED, *ser], None
    return [status, *ser], None


def dag_decode_body(ctx, body):
    """Decode a DAG message body (status byte stripped by the caller's
    slicing here).  `body` may be bytes (inline) or a pinned arena view
    (spilled, via recv_view) — the caller releases it AFTER this
    returns; no reference into the view survives."""
    payload = memoryview(body)[1:]
    if payload[:8] == MAGIC_LOCAL:
        token, skeleton, _specs = ctx.deserialize(payload[8:])
        return join_device_leaves(skeleton, take_local(token))
    if payload[:8] == MAGIC_STAGED:
        v = ctx.deserialize(payload[8:])
        return detach_host_leaves(v, payload)
    if not isinstance(body, (bytes, bytearray)):
        # Unmarked spilled payload: preserve the copy-out discipline —
        # host ndarray leaves may alias the view as pickle-5 buffers.
        payload = memoryview(bytes(payload))
    return ctx.deserialize(payload)
