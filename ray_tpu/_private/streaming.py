"""Streaming generators: tasks that yield a stream of objects.

Equivalent of the reference's streaming generator machinery
(reference: python/ray/remote_function.py:404-410 num_returns="streaming",
python/ray/_raylet.pyx:939 streaming-generator execution context,
python/ray/_private/streaming_generator.py ObjectRefGenerator): a task or
actor method declared with ``num_returns="streaming"`` returns an
:class:`ObjectRefGenerator` immediately; each value the remote generator
yields becomes its own owner-owned object the caller can consume while the
task is still running.

Wire design (TPU-native, no Cython): the executing worker streams each
yielded item to the owner as a ``stream_item`` RPC over the worker→owner
peer connection (the same socket the borrow/escape protocol rides), with a
small in-flight window for pipelining, and finishes with an ordered
``stream_end``.  Caller-side backpressure is the *ack*: when the consumer
lags more than ``_generator_backpressure_num_objects`` items, the owner
simply delays the stream_item reply, which stalls the producer's window —
no separate credit channel (reference: generator_waiter.cc waits on a
consumed-offset watermark; the delayed ack is the same watermark folded
into the RPC we already send).

Item ``i`` (0-based) is stored under return index ``i + 2`` of the task —
index 1 is the generator's *completion* object, which resolves (via the
normal push-task reply path) to None on success or the task's exception,
so ``gen.completed()`` composes with get/wait like any ref.

Producer-side cleanup is DETERMINISTIC: when the consumer abandons or
cancels the stream, the executing worker acloses the user's (async)
generator immediately (worker_main._run_streaming), not at a later GC
cycle — the contract the LLM serving path relies on to retire a
cancelled request and free its KV pages mid-decode.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Set

from .ids import ObjectID, TaskID

# Item i of task T lives at return index i + _ITEM_BASE (index 1 is the
# completion ref).
_ITEM_BASE = 2


def item_object_id(task_id: bytes, index: int) -> bytes:
    return ObjectID.for_task_return(TaskID(task_id),
                                    index + _ITEM_BASE).binary()


class StreamState:
    """Owner-side bookkeeping for one in-flight streaming generator."""

    __slots__ = ("task_id", "ready", "seen", "consumed", "produced",
                 "total", "errored", "event", "consume_event", "bp",
                 "released", "expected_attempt")

    def __init__(self, task_id: bytes, backpressure: int = 0,
                 expected_attempt: int = 0):
        self.task_id = task_id
        self.ready: Set[int] = set()    # arrived, unconsumed item indices
        self.seen: Set[int] = set()     # every index ever pinned (survives
        #                                 retry resets: pins are not retaken)
        self.consumed = 0               # next index the consumer takes
        self.produced = 0               # high-water arrival mark this attempt
        self.total: Optional[int] = None
        self.errored = False            # stream ended with an error: the
        #                                 completion ref holds the exception
        self.event = asyncio.Event()          # producer -> consumer wakeup
        self.consume_event = asyncio.Event()  # consumer -> delayed-ack wakeup
        self.bp = backpressure
        self.released = False
        # Messages are stamped with the producing attempt (the spec's
        # retries_left at dispatch); a stale stream_end from a dead attempt
        # must not finalize the retried attempt's stream.
        self.expected_attempt = expected_attempt

    # ------------------------------------------------------------ producer --
    def unconsumed(self) -> int:
        return self.produced - self.consumed

    def item_arrived(self, index: int) -> bool:
        """Record arrival; True if this index needs pinning (first sight)."""
        first = index not in self.seen
        self.seen.add(index)
        if index >= self.consumed:
            self.ready.add(index)
        self.produced = max(self.produced, index + 1)
        self.event.set()
        return first

    def finish(self, total: int, errored: bool) -> None:
        self.total = total
        self.errored = errored
        self.event.set()
        # Unblock any ack parked on backpressure: the stream is over.
        self.consume_event.set()

    def reset(self) -> None:
        """The executing worker died and the task is being retried: the new
        attempt regenerates every item from 0 (reference: streaming tasks
        re-execute whole on retry).  Consumed refs stay consumed — the
        re-arriving items simply refresh their stored values under the same
        deterministic ids."""
        self.ready.clear()
        self.produced = self.consumed
        self.total = None
        self.errored = False
        self.consume_event.set()

    # ------------------------------------------------------------ consumer --
    async def next_index(self) -> Optional[int]:
        """Index of the next ready item, or None when the stream is
        exhausted (caller then raises StopIteration or fetches the
        completion ref's exception if `errored`)."""
        while True:
            if self.consumed in self.ready:
                idx = self.consumed
                self.ready.discard(idx)
                self.consumed += 1
                # Wake delayed acks; re-arm so the next over-budget item
                # parks again.
                self.consume_event.set()
                self.consume_event = asyncio.Event()
                return idx
            if self.total is not None and self.consumed >= self.total:
                return None
            if self.total is not None and self.errored:
                # Errored stream with a delivery gap: in-flight emissions
                # were cancelled when the generator raised, so items past
                # the gap will never arrive — end iteration here (the
                # consumer then raises the completion ref's exception).
                return None
            self.event.clear()
            await self.event.wait()


class ObjectRefGenerator:
    """Caller-side handle to a streaming generator task (reference:
    python/ray/_private/streaming_generator.py ObjectRefGenerator —
    usable as both a sync and an async iterator; each __next__ returns an
    ObjectRef that is already resolvable locally)."""

    def __init__(self, core, task_id: bytes, completed_ref):
        self._core = core
        self._task_id = task_id
        self._completed_ref = completed_ref

    # -------------------------------------------------------------- iter ----
    def __iter__(self):
        return self

    def __next__(self):
        ref = self._core.stream_next(self._task_id)
        if ref is None:
            self._raise_if_errored_sync()
            raise StopIteration
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self):
        ref = await self._core.stream_next_async(self._task_id)
        if ref is None:
            await self._raise_if_errored_async()
            raise StopAsyncIteration
        return ref

    # ------------------------------------------------------------ control ---
    def completed(self):
        """Ref that resolves when the generator finishes (None on success,
        raises the task's exception on failure)."""
        return self._completed_ref

    def task_id(self) -> bytes:
        return self._task_id

    def _raise_if_errored_sync(self):
        if self._core.stream_errored(self._task_id):
            # The completion ref holds the exception; get() raises it.
            self._core.get([self._completed_ref], timeout=30)

    async def _raise_if_errored_async(self):
        if self._core.stream_errored(self._task_id):
            await self._core.get_async(self._completed_ref, timeout=30)

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()})"

    def __del__(self):
        core = self._core
        if core is not None:
            try:
                core.release_stream(self._task_id)
            except Exception:
                pass
