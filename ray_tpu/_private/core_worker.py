"""Per-process core runtime: task submission, objects, actors.

Equivalent of the reference's CoreWorker (reference:
src/ray/core_worker/core_worker.h:167) linked into every driver and worker:

- task submission with per-scheduling-key lease caching and pipelining
  (reference: task_submission/normal_task_submitter.cc:70 — leases are reused
  for tasks with the same scheduling key; here we additionally pipeline a
  small number of pushes per leased worker to hide RPC latency)
- dependency resolution: pending/small args are awaited and inlined into the
  spec; large args travel by reference (reference: dependency_resolver.cc)
- in-process memory store for small results + shared-memory store for large
  ones (reference: memory_store/ + plasma_store_provider.h)
- ownership: the submitting process owns task returns and puts, serves their
  values to borrowers over its RPC server, and frees primary copies when
  reference counts drop to zero (reference: reference_count.cc)
- actor task submission over direct worker connections with per-handle
  sequence numbers (reference: actor_task_submitter.cc,
  sequential_actor_submit_queue.cc)

The driver runs the asyncio loop on a daemon thread and the public sync API
bridges via run_coroutine_threadsafe; workers run the loop in the foreground
(worker_main.py) and execute user code on executor threads.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import threading
import time
import traceback
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from .. import exceptions as exc
from ..object_ref import ObjectRef
from . import clocks, deadlines, protocol, rpc
from . import flight_recorder as frec
from .config import get_config
from .ids import (ActorID, JobID, ObjectID, TaskID, WorkerID,
                  fast_actor_task_id)
from .memory_store import MemoryStore
from .reference_counter import ReferenceCounter
from .serialization import get_context
from .shm_store import ShmStore, StoreFullError
from .streaming import ObjectRefGenerator, StreamState, item_object_id

logger = logging.getLogger("ray_tpu.core_worker")

# Set (by worker_main._run_sync) on executor threads while USER task code
# runs: a get()/wait() that is about to block on such a thread notifies
# the node agent so the lease's CPU is released for queued work
# (reference: NotifyDirectCallTaskBlocked, core_worker.cc — deadlock
# avoidance for tasks that block on results of tasks they submitted).
task_exec_tls = threading.local()


def _release_read_pin(store: ShmStore, oid: bytes) -> None:
    """weakref.finalize target for _pinned views: runs from GC on any
    thread, possibly at interpreter exit after the store detached — both
    must be harmless (the C refcount ops are atomic; a freed object's
    release is a no-op in the store)."""
    try:
        if store._h is not None:
            store.release(oid)
    except Exception:
        pass

# Floor of the ADAPTIVE in-flight window per leased worker.  A granted
# lease still RUNS one task at a time (the worker's task lock serializes
# execution, matching reference semantics); pipelined pushes hide the
# push/complete round trip so tiny-task throughput isn't bounded by
# per-task RTT.  The window starts here and grows toward
# max_tasks_in_flight_per_worker while observed latency stays low
# (_note_task_latency), shrinking back on backpressure or lease loss —
# so long tasks never pile onto one worker while other nodes idle, the
# queue drains back through _pump when a lease dies, and queued-at-worker
# tasks remain cancellable (_cancel_requested check before execution).
PIPELINE_DEPTH = 3


class _PendingTask:
    __slots__ = ("spec", "ref_args", "borrowed_args")

    def __init__(self, spec: dict, ref_args: List[bytes],
                 borrowed_args: Optional[List[tuple]] = None):
        self.spec = spec
        self.ref_args = ref_args  # owned object ids pinned while in flight
        # (oid, owner_addr) pairs of borrowed refs nested in arg values:
        # escape-pinned at the remote owner until the reply lands.
        self.borrowed_args = borrowed_args or []


class _Lease:
    __slots__ = ("lease_id", "worker_addr", "worker_id", "conn", "inflight",
                 "agent_conn", "idle_since", "epoch")

    def __init__(self, lease_id, worker_addr, worker_id, conn, agent_conn,
                 epoch=0):
        self.lease_id = lease_id
        self.worker_addr = worker_addr
        self.worker_id = worker_id
        self.conn = conn
        self.agent_conn = agent_conn
        self.inflight = 0
        self.idle_since = time.monotonic()
        # Cluster epoch the grant was minted under (GCS HA fencing):
        # idle leases from an older epoch are dropped on epoch bump.
        self.epoch = epoch


class _KeyState:
    __slots__ = ("queue", "leases", "pending_lease_requests", "resources",
                 "strategy", "runtime_env", "last_demand_report",
                 "lease_backoff_until", "pump_scheduled", "avg_task_s",
                 "prefix", "prefix_blob", "window")

    def __init__(self, resources, strategy, runtime_env=None):
        self.queue: deque[_PendingTask] = deque()
        self.leases: List[_Lease] = []
        self.pending_lease_requests = 0
        self.resources = resources
        self.strategy = strategy
        self.runtime_env = runtime_env
        self.last_demand_report = 0.0
        self.lease_backoff_until = 0.0
        self.pump_scheduled = False
        # EMA of push->complete latency; drives the adaptive window.
        self.avg_task_s: Optional[float] = None
        # Stable spec prefix shared by every task of this key (and its
        # one-time msgpack encoding) — see protocol.spec_prefix_of.
        # Seeded from RemoteFunction._submit_cache when available, else
        # built from the first pushed spec.
        self.prefix: Optional[dict] = None
        self.prefix_blob: Optional[bytes] = None
        # Adaptive in-flight window per lease (PIPELINE_DEPTH ..
        # max_tasks_in_flight_per_worker): grows on low RTT, shrinks on
        # transport backpressure / lease loss.
        self.window = PIPELINE_DEPTH


class _ActorState:
    __slots__ = ("actor_id", "address", "conn", "seq", "dead", "death_cause",
                 "resolving", "submit_queue", "draining", "drain_scheduled",
                 "out_of_order", "prefix", "prefix_blob")

    def __init__(self, actor_id: bytes):
        # Stable spec prefix for this handle's calls (the actor-method
        # equivalent of RemoteFunction's submit cache): method/seq/args
        # travel as per-call deltas.
        self.prefix: Optional[dict] = None
        self.prefix_blob: Optional[bytes] = None
        self.actor_id = actor_id
        self.address = None
        self.conn: Optional[rpc.Connection] = None
        self.seq = 0
        self.dead = False
        self.death_cause = ""
        self.resolving: Optional[asyncio.Future] = None
        # Per-actor submission pipeline: oversized-arg plasma puts complete
        # in order before the push is scheduled, so a later small-arg call
        # cannot overtake an earlier large-arg one.
        self.submit_queue: deque = deque()
        self.draining = False
        self.drain_scheduled = False
        # allow_out_of_order_execution actors use the out-of-order submit
        # queue: dep resolution per call, no head-of-line blocking
        # (reference: out_of_order_actor_submit_queue.cc vs
        # sequential_actor_submit_queue.cc).
        self.out_of_order = False


class CoreWorker:
    def __init__(self, *, mode: str, gcs_address, agent_address,
                 store_path: str, node_id: bytes, session_dir: str,
                 job_id: Optional[bytes] = None,
                 worker_id: Optional[bytes] = None):
        self.mode = mode
        self.gcs_address = tuple(gcs_address)
        self.agent_address = tuple(agent_address)
        self.node_id = node_id
        self.session_dir = session_dir
        # Cluster epoch (GCS HA fencing, docs/control_plane.md §8):
        # learned from grants/rejections, stamped into every lease
        # request so a fenced-off owner is told to refresh instead of
        # silently acting on a pre-failover view.
        self.cluster_epoch = protocol.EPOCH_NONE
        self.stale_epoch_rejections = 0
        self.worker_id = worker_id or WorkerID.from_random().binary()
        self.job_id = job_id
        self.store = ShmStore.attach(store_path)
        self.memory_store = MemoryStore()
        # ref id -> device array (RDT equivalent; experimental/).
        self.device_objects: Dict[bytes, Any] = {}
        self.reference_counter = ReferenceCounter(self._on_ref_zero)
        self.current_task_id: bytes = b""
        # Owner task for puts made outside any executing task (threads the
        # user starts inside actors); minted lazily once job_id is known.
        self._process_task_id_cache: Optional[bytes] = None
        self.current_actor_id: Optional[bytes] = None  # set in actor workers
        self._put_counter = 0
        self._keys: Dict[bytes, _KeyState] = {}
        self._actors: Dict[bytes, _ActorState] = {}
        self._worker_conns: Dict[tuple, rpc.Connection] = {}
        self._owner_conns: Dict[tuple, rpc.Connection] = {}
        self._fn_cache: Dict[bytes, Any] = {}
        self._pg_cache: Dict[bytes, dict] = {}
        self._packaged_envs: Dict[str, dict] = {}
        self._pg_rr: Dict[bytes, int] = {}
        self.current_placement_group: Optional[dict] = None
        self._inflight_replies: Dict[bytes, asyncio.Future] = {}
        self._recovering: Dict[bytes, asyncio.Future] = {}
        self._cancelled: set = set()               # task ids cancelled
        # Task ids whose end-to-end deadline fired owner-side: their
        # return refs already resolved to DeadlineExceededError, so a
        # late reply (or the cancel path's TaskCancelledError) must not
        # overwrite that typed outcome — only bookkeeping runs.
        self._deadline_expired: set = set()
        # task_id -> armed call_later handle; cancelled when the task
        # resolves so a deadline can never fire on a task that already
        # completed (and whose freed return entries it would resurrect).
        self._deadline_timers: Dict[bytes, Any] = {}
        # task_id -> asyncio.Task finishing a deferred submission (fn
        # export / dep resolution); _cancel interrupts these directly.
        self._resolving: Dict[bytes, asyncio.Task] = {}
        # task_id -> StreamState for in-flight streaming generators we own.
        self._streams: Dict[bytes, StreamState] = {}
        self._inflight_tasks: Dict[bytes, _Lease] = {}        # normal tasks
        self._inflight_actor_tasks: Dict[bytes, _ActorState] = {}
        # task_id -> completion record for batched pushes: ("n", key,
        # state, lease, task, t_push) for normal tasks, ("a", astate,
        # conn, task, t_push) for actor calls (the conn the batch was
        # pushed on — astate.conn may already point at a reconnect by
        # the time the old conn's loss cleanup runs).  Resolved by
        # complete_batch frames (_f_complete_batch) or by
        # connection-loss cleanup.
        self._pending_replies: Dict[bytes, tuple] = {}
        # actor_id -> future of an in-flight background registration this
        # process initiated; _actor_conn awaits it instead of polling GCS.
        self._registering: Dict[bytes, asyncio.Future] = {}
        # Task status/profile events, flushed to the GCS sink periodically
        # (reference: core_worker/task_event_buffer.h:297 AddTaskEvent /
        # FlushEvents). Bounded: drops oldest under pressure — counted,
        # reported with every flush (no silent caps).
        self._task_events: deque = deque(maxlen=10000)
        self._task_events_dropped = 0
        # Flight-recorder rows whose flush notify failed, kept for the
        # next telemetry tick (bounded at ring capacity; overflow folds
        # into the recorder's drop counter — no silent loss).
        self._frec_retry: List[dict] = []
        self._seq_lock = threading.Lock()   # seq/put-id minting, any thread
        # Cross-thread submission mailbox: caller threads append closures
        # and schedule ONE loop wakeup per burst instead of one
        # call_soon_threadsafe (self-pipe write + epoll wake) per call —
        # the dominant submit-side syscall cost under task fan-out.
        self._mailbox: deque = deque()
        self._mailbox_scheduled = False
        # channel -> [callback] for GCS pubsub fan-in (see subscribe()).
        self._pubsub_handlers: Dict[str, list] = {}
        self._gcs_subscribed: set = set()   # channels subscribed at GCS
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self.gcs: Optional[rpc.Connection] = None
        self.agent: Optional[rpc.Connection] = None
        self.address: Optional[tuple] = None
        self._server: Optional[rpc.RpcServer] = None
        self.executor = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="ray_tpu_exec")
        self._shutdown = False
        # Hung-task tracker (diagnosis plane); armed by the worker main
        # when diagnosis_enabled — record_task_event feeds it.
        self._diag_tracker = None
        cfg = get_config()
        self._inline_limit = cfg.max_direct_call_object_size
        self._max_inflight = max(PIPELINE_DEPTH,
                                 cfg.max_tasks_in_flight_per_worker)
        self._ack_timeout = cfg.submit_batch_ack_timeout_s
        ctx = get_context()
        ctx.ref_factory = self._ref_factory
        ctx.ref_hook = self._ref_serialized_hook

    # ------------------------------------------------------------ lifecycle --
    def start_driver(self):
        """Start the loop on a daemon thread and connect (driver mode)."""
        ready = threading.Event()

        def _run():
            self.loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self.loop)
            rpc.enable_eager_tasks(self.loop)
            self.loop.run_until_complete(self._connect())
            ready.set()
            self.loop.run_forever()

        self._loop_thread = threading.Thread(target=_run, daemon=True,
                                             name="ray_tpu_io")
        self._loop_thread.start()
        if not ready.wait(30):
            raise TimeoutError("driver core worker failed to start")
        if self.job_id is None:
            self.job_id = JobID.from_int(
                self._run(self.gcs.call("next_job_id", {}))).binary()
        self.current_task_id = TaskID.for_driver(JobID(self.job_id)).binary()
        self._run(self.gcs.call("register_job", {
            "job_id": self.job_id, "driver_addr": list(self.address)}))

    async def start_in_loop(self):
        """Connect using the already-running loop (worker mode)."""
        self.loop = asyncio.get_running_loop()
        rpc.enable_eager_tasks(self.loop)
        await self._connect()

    async def _connect(self):
        # Chaos wiring: the rpc_chaos config ('Method=N:req%:resp%',
        # reference: rpc_chaos.cc RAY_testing_rpc_failure) applies to
        # every process whose config carries it — set
        # RAY_TPU_rpc_chaos in the environment to inject cluster-wide.
        # Unconditional: an empty spec CLEARS injection, so a chaos-free
        # init() after a chaos session in the same process doesn't
        # inherit the old rules through the module global.
        cfg = get_config()
        rpc.enable_chaos(cfg.rpc_chaos)
        rpc.enable_link_chaos(cfg.link_chaos)
        # Wire hot path: resolve the framer mode ONCE per process from
        # config (a per-node _system_config reaches workers through the
        # agent-forwarded env) so every connection this process opens
        # agrees — mixed modes are a per-NODE property, never per-conn.
        rpc.enable_native_framer(cfg.rpc_native_framer)
        # Gray-failure defense: unary control calls get a default bound
        # so a half-open connection can never hang this process forever
        # (explicit timeout=0 at a call site opts out).
        rpc.set_default_call_timeout(cfg.control_call_timeout_s)
        self._server = rpc.RpcServer(self._handlers(), name=f"cw-{self.mode}")
        self.address = await self._server.start_tcp("127.0.0.1", 0)
        # Reconnecting: calls issued across a GCS restart re-dial and
        # retry once (mutations are id-keyed upserts, so replays are
        # idempotent).
        self.gcs = rpc.ReconnectingConnection(
            self.gcs_address, name="cw->gcs",
            handlers={"pubsub": self.h_pubsub},
            on_reconnect=self._resubscribe,
            # GCS failover re-homing: every dial re-reads the session's
            # advertised-address file, so a promoted standby (new port)
            # is found through the same jittered reconnect backoff.
            resolver=lambda: protocol.resolve_gcs_address(
                self.session_dir, fallback=self.gcs_address))
        await self.gcs.ensure()
        self.agent = await rpc.connect(self.agent_address, name="cw->agent")
        self._spawn(self._telemetry_flush_loop())

    def _handlers(self):
        return {
            "get_object": self.h_get_object,
            "free_notify": self.h_free_notify,
            "borrow_add": self.h_borrow_add,
            "borrow_release": self.h_borrow_release,
            "escape_pin": self.h_escape_pin,
            "escape_release": self.h_escape_release,
            "recover_object": self.h_recover_object,
            "object_locations": self.h_object_locations,
            "object_location_add": self.h_object_location_add,
            "object_location_remove": self.h_object_location_remove,
            "device_fetch": self.h_device_fetch,
            "device_free": self.h_device_free,
            "stream_item": self.h_stream_item,
            "stream_end": self.h_stream_end,
            "ping": lambda conn, p: "pong",
        }

    # Streaming generators (reference: _raylet.pyx:939 streaming-generator
    # execution; see _private/streaming.py for the wire design).
    async def h_stream_item(self, conn, p):
        """An executing generator yielded item `index`; store it under its
        deterministic id and ack — the ack is delayed while the consumer
        lags more than the configured backpressure, which stalls the
        producer's in-flight window (reference: generator_waiter.cc
        consumed-offset watermark)."""
        tid, idx, entry = p["task_id"], p["index"], p["entry"]
        st = self._streams.get(tid)
        if st is None or st.released:
            return {"dropped": True}   # consumer released the generator
        if p.get("attempt", 0) != st.expected_attempt:
            return True                # straggler from a dead attempt
        if idx < st.consumed:
            # Retry re-delivery of an item the consumer already took: its
            # ObjectRef (if still held) keeps the original value; if it was
            # dropped, the object is freed — re-storing would resurrect an
            # untracked entry that never gets released.
            return True
        oid = item_object_id(tid, idx)
        first = st.item_arrived(idx)
        if first:
            self.reference_counter.add_owned(oid)
            # Held by the stream until the consumer takes the item (or the
            # generator is released) — there is no ObjectRef yet.
            self.reference_counter.add_escape_pin(oid)
            nested = [(bytes(noid),
                       None if tuple(nowner) == self.address
                       else tuple(nowner))
                      for noid, nowner in entry.get("nested", [])]
            for noid, nowner in nested:
                if nowner is None:
                    # Our own refs nested in the item: we take the pin
                    # here, synchronously before the ack (same
                    # reply-carried-pin protocol as _handle_reply).
                    self.reference_counter.add_escape_pin(noid)
            if nested:
                self._record_contained(oid, nested, take_pins=False)
        # Duplicates (unconsumed re-delivery after a retry) refresh the
        # stored entry — the plasma copy moved to the new attempt's node —
        # but never re-take ownership or nested pins.
        if "inline" in entry:
            self.memory_store.put_inline(oid, entry["inline"])
        else:
            self.memory_store.put_plasma_location(
                oid, entry["plasma"], size=entry.get("size"))
        while (st.bp and st.unconsumed() >= st.bp and st.total is None
               and not st.released):
            ev = st.consume_event
            await ev.wait()
        return True

    async def h_stream_end(self, conn, p):
        st = self._streams.get(p["task_id"])
        if st is not None and p.get("attempt", 0) == st.expected_attempt:
            st.finish(p["count"], bool(p.get("errored")))
        return True

    async def stream_next_async(self, task_id: bytes):
        st = self._streams.get(task_id)
        if st is None:
            return None
        idx = await st.next_index()
        if idx is None:
            return None
        oid = item_object_id(task_id, idx)
        ref = ObjectRef(oid, self.address, worker=self)
        # The ObjectRef's local ref now keeps the item alive; drop the
        # stream's pin (ordered: pin released only after add_local_ref).
        self.reference_counter.release_escape_pin(oid)
        return ref

    def stream_next(self, task_id: bytes):
        return self._run(self.stream_next_async(task_id))

    def stream_errored(self, task_id: bytes) -> bool:
        st = self._streams.get(task_id)
        return st is not None and st.errored

    def register_stream(self, task_id: bytes, backpressure: int = 0,
                        expected_attempt: int = 0):
        self._streams[task_id] = StreamState(task_id, backpressure,
                                             expected_attempt)

    def release_stream(self, task_id: bytes):
        """Drop a generator the consumer abandoned: free unconsumed items
        and let parked producer acks return (the producer sees `dropped`
        on its next item and stops).  Safe from any thread (__del__)."""
        st = self._streams.get(task_id)
        if st is None or st.released:
            return
        loop = self.loop
        if loop is None or loop.is_closed():
            return

        def _release():
            stt = self._streams.pop(task_id, None)
            if stt is None or stt.released:
                return
            stt.released = True
            stt.consume_event.set()
            stt.event.set()
            for idx in stt.seen:
                if idx >= stt.consumed:
                    oid = item_object_id(task_id, idx)
                    self.reference_counter.release_escape_pin(oid)
                    self.memory_store.delete(oid)

        loop.call_soon_threadsafe(_release)

    def _stream_reset_for_retry(self, spec):
        if spec.get("streaming"):
            st = self._streams.get(spec["task_id"])
            if st is not None:
                st.reset()   # the new attempt regenerates every item
                # Only messages stamped with the retried attempt (the
                # decremented retries_left) finalize the stream now.
                st.expected_attempt = spec["retries_left"]

    def _stream_on_task_failed(self, spec):
        """Task-level failure (error reply, crash out of retries, cancel):
        finalize so iteration drains arrived items then raises the
        completion ref's stored exception."""
        st = self._streams.get(spec["task_id"])
        if st is not None and st.total is None:
            st.finish(st.produced, errored=True)

    # Device-resident objects (RDT equivalent — see experimental/
    # device_objects.py; reference: gpu_object_manager).  Transfers are
    # CHUNKED: one msgpack frame per chunk keeps multi-GB arrays under
    # the RPC frame cap (like the agent's object plane, h_pull_object).
    _DEVICE_CHUNK = 64 * 1024 * 1024

    async def h_device_fetch(self, conn, p):
        entry = self.device_objects.get(p["object_id"])
        if entry is None:
            return None
        offset = p.get("offset", 0)
        import numpy as np

        def _stage():
            # Device->host readback + copy off the event loop: a multi-GB
            # transfer must not stall the owner's RPC handling.
            arr = np.asarray(entry)
            flat = arr.reshape(-1).view(np.uint8)
            total = flat.nbytes
            chunk = bytes(flat[offset:offset + self._DEVICE_CHUNK])
            # Copy audit: count the staged bytes actually shipped (per
            # chunk, so the cumulative series equals bytes transferred).
            from . import device_plane
            device_plane.record_d2h(len(chunk))
            return {"data": chunk, "total": total, "offset": offset,
                    "dtype": str(arr.dtype), "shape": list(arr.shape)}

        return await asyncio.get_running_loop().run_in_executor(
            self.executor, _stage)

    async def h_device_free(self, conn, p):
        self.device_objects.pop(p["object_id"], None)
        return True

    # Owner-side borrower-ledger service (reference: reference counting RPCs
    # folded into CoreWorkerService).
    async def h_borrow_add(self, conn, p):
        # Same staleness/resurrection rules as the reply path: never
        # recreate a freed ref record, honor release tombstones by epoch.
        self.reference_counter.add_borrower_from_reply(
            p["object_id"], p["worker_id"], epoch=p.get("epoch", 0))
        return True

    async def h_borrow_release(self, conn, p):
        self.reference_counter.remove_borrower(
            p["object_id"], p["worker_id"], epoch=p.get("epoch", 0))
        return True

    async def h_escape_pin(self, conn, p):
        self.reference_counter.add_escape_pin(p["object_id"])
        return True

    async def h_escape_release(self, conn, p):
        self.reference_counter.release_escape_pin(p["object_id"])
        return True

    async def h_recover_object(self, conn, p):
        """A borrower lost the primary copy: reconstruct it for them."""
        return await self._recover_object(p["object_id"])

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        if self.loop and self._loop_thread:
            def _drain_and_stop():
                # Cancel background tasks and WAIT (bounded) for them to
                # unwind before stopping, so teardown is quiet (no 'Task
                # was destroyed but it is pending') — a task awaiting a
                # nested future needs several loop iterations to finish
                # cancelling, not one.
                async def _finish():
                    cur = asyncio.current_task()
                    tasks = [t for t in asyncio.all_tasks() if t is not cur]
                    for t in tasks:
                        t.cancel()
                    if tasks:
                        await asyncio.wait(tasks, timeout=1.0)
                    self.loop.stop()
                rpc.spawn(_finish())
            self.loop.call_soon_threadsafe(_drain_and_stop)
            self._loop_thread.join(timeout=5)
        self.executor.shutdown(wait=False)
        self.store.close()

    def gcs_call(self, method: str, payload: dict, timeout: float = 60):
        """Synchronous GCS RPC for API modules (placement groups, state)."""
        return self._run(self.gcs.call(method, payload, timeout=timeout))

    def _spawn(self, coro) -> asyncio.Task:
        """ensure_future with a strong reference held until completion."""
        return rpc.spawn(coro)

    # -------------------------------------------------------------- pubsub --
    async def h_pubsub(self, conn, p):
        for cb in list(self._pubsub_handlers.get(p["channel"], [])):
            try:
                cb(p["message"])
            except Exception:
                logger.exception("pubsub callback failed (%s)", p["channel"])
        return True

    async def _resubscribe(self, conn):
        # Snapshot: a concurrent first-time subscribe() may add channels
        # while we await (set-changed-during-iteration otherwise).
        for channel in list(self._gcs_subscribed):
            await conn.call("subscribe", {"channel": channel})

    def subscribe(self, channel: str, callback) -> None:
        """Register callback(message) for a GCS pubsub channel (reference:
        GcsSubscriber). Thread-safe; callbacks run on the event loop.
        Subscriptions survive GCS reconnects (re-registered in
        _resubscribe)."""
        def _do():
            self._pubsub_handlers.setdefault(channel, []).append(callback)
            if channel not in self._gcs_subscribed:
                # Once per (connection, channel): the GCS appends the conn
                # to the channel's subscriber list unconditionally, so a
                # re-subscribe would duplicate every notify.
                self._gcs_subscribed.add(channel)
                self._spawn(self.gcs.call("subscribe",
                                          {"channel": channel}))
        if self._on_loop_thread():
            _do()
        else:
            self.loop.call_soon_threadsafe(_do)

    def unsubscribe(self, channel: str, callback) -> None:
        """Remove a callback registered with subscribe() (thread-safe).
        The GCS-side channel subscription persists (harmless: messages
        with no local handlers are dropped)."""
        def _do():
            lst = self._pubsub_handlers.get(channel)
            if lst and callback in lst:
                lst.remove(callback)
            if lst is not None and not lst:
                del self._pubsub_handlers[channel]
        if self._on_loop_thread():
            _do()
        else:
            self.loop.call_soon_threadsafe(_do)

    def publish(self, channel: str, message) -> None:
        """Fire-and-forget publish (thread-safe)."""
        def _do():
            self._spawn(self.gcs.call("publish", {
                "channel": channel, "message": message}))
        if self._on_loop_thread():
            _do()
        else:
            self.loop.call_soon_threadsafe(_do)

    def _post_to_loop(self, fn) -> None:
        """Run `fn` on the event loop, coalescing a burst of cross-thread
        posts into one loop wakeup.  deque.append is GIL-atomic; the
        flag race (append landing as the drain exits) is closed by the
        drain's re-check."""
        self._mailbox.append(fn)
        if not self._mailbox_scheduled:
            self._mailbox_scheduled = True
            self.loop.call_soon_threadsafe(self._drain_mailbox)

    def _drain_mailbox(self) -> None:
        mb = self._mailbox
        while mb:
            try:
                fn = mb.popleft()
            except IndexError:
                break   # raced another drain
            try:
                fn()
            except Exception:
                logger.exception("mailbox callback failed")
        self._mailbox_scheduled = False
        if mb:
            # An append raced the flag reset: make sure it runs.
            self._mailbox_scheduled = True
            self.loop.call_soon(self._drain_mailbox)

    async def _agent_list_objects(self, agent_addr: tuple,
                                  limit: int = 10_000):
        conn = await rpc.connect(agent_addr, name="cw->agent-state",
                                 retries=2)
        try:
            return await conn.call("list_objects", {"limit": limit},
                                   timeout=20)
        finally:
            await conn.close()

    # ---------------------------------------------------------- telemetry ---
    def record_task_event(self, task_id: bytes, name: str, event: str,
                          **extra):
        """Buffer one task status/profile event; any thread. Stored as a
        tuple — the flush loop expands to the wire dict, so the per-call
        hot path pays one append instead of a 7-key dict build.

        Stamps clocks.wall() (skew-injectable) so cross-node alignment
        applies to these events like every other telemetry source.  The
        deque's silent oldest-drop is counted: the total rides every
        flush to the GCS sink, which surfaces it through the state API
        instead of presenting a truncated stream as complete."""
        if len(self._task_events) == self._task_events.maxlen:
            self._task_events_dropped += 1
        self._task_events.append(
            (task_id, name, event, clocks.wall(), extra or None))
        if self._diag_tracker is not None:
            self._diag_tracker.note(task_id, name, event)

    async def _telemetry_flush_loop(self):
        """Periodic push of buffered task events + metric deltas to the
        GCS sinks (reference: TaskEventBuffer::FlushEvents +
        metrics_agent)."""
        from ..util import metrics as _metrics
        interval = get_config().task_event_flush_interval_s
        export_metrics = get_config().metrics_export_enabled
        while not self._shutdown:
            await asyncio.sleep(interval)
            recorder_rows = self._frec_retry + frec.recorder().drain(
                node_id=self.node_id or b"",
                worker_id=self.worker_id or b"")
            self._frec_retry = []
            if self._task_events or recorder_rows:
                raw = []
                while self._task_events:
                    raw.append(self._task_events.popleft())
                wid, nid, jid = self.worker_id, self.node_id, \
                    self.job_id or b""
                batch = []
                for task_id, name, event, ts, extra in raw:
                    rec = {"task_id": task_id, "name": name, "event": event,
                           "ts": ts, "worker_id": wid, "node_id": nid,
                           "job_id": jid}
                    if extra:
                        rec.update(extra)
                    batch.append(rec)
                # Flight-recorder rows ride the SAME batched notify —
                # the no-new-per-event-RPCs discipline.
                batch.extend(recorder_rows)
                try:
                    # Pre-packed blob: the GCS stores it opaquely (no
                    # per-event msgpack decode on its loop) and expands
                    # lazily at query time — under actor-call fan-out the
                    # event stream is ~3 events/call and GCS-side decode
                    # was a measurable share of the core's CPU.
                    self.gcs.notify("task_events", {
                        "blob": rpc._pack(batch), "n": len(batch),
                        "src": wid,
                        "dropped": (self._task_events_dropped
                                    + frec.recorder().dropped)})
                except Exception:
                    # Transient GCS outage: put the batch back for the
                    # next interval (deque maxlen bounds memory), and
                    # keep the recorder rows too — BOTH bounded, with
                    # overflow COUNTED (no silent loss): extendleft on
                    # a full deque evicts from the opposite (newest)
                    # end, so count what the re-queue itself sheds.
                    overflow = (len(self._task_events) + len(raw)
                                - (self._task_events.maxlen or 0))
                    if overflow > 0:
                        self._task_events_dropped += min(overflow,
                                                         len(raw))
                    self._task_events.extendleft(reversed(raw))
                    cap = frec.recorder().capacity
                    keep = recorder_rows[-cap:]
                    frec.recorder().note_lost(
                        len(recorder_rows) - len(keep))
                    self._frec_retry = keep
            snap = _metrics.registry_snapshot()
            if export_metrics:
                snap = snap + self._runtime_metrics()
            if snap:
                try:
                    self.gcs.notify("report_metrics", {
                        "worker_id": self.worker_id,
                        "node_id": self.node_id,
                        "metrics": snap})
                except Exception:
                    pass

    def _runtime_metrics(self) -> List[dict]:
        """This process's runtime series for the unified export: RPC
        io_stats, copy-audit totals, adaptive submit-window sizes, event
        drop counters.  Same row shape as util.metrics snapshots;
        node_id is stamped at the source (user metrics keep their own
        label sets) and the GCS sums counters across reporters."""
        now = time.time()
        lab = {"proc": "driver" if self.mode == "driver" else "worker",
               "node_id": (self.node_id or b"").hex()}

        def row(name, value, typ="counter", help_="", labels=None):
            return {"name": name, "type": typ, "help": help_, "ts": now,
                    "labels": labels or lab, "value": float(value)}

        out = [
            row("ray_tpu_task_events_buffer_dropped_total",
                self._task_events_dropped,
                help_="task events dropped by this process's bounded "
                      "buffer before flush"),
        ]
        # Common per-process rows (io_stats, copy audit, recorder
        # counters): shared with the agent's export so the two cannot
        # diverge.
        out.extend(frec.export_rows(lab))
        # Adaptive submit windows (control-plane pipelining depth, one
        # per scheduling key): the widest current window is the useful
        # scalar — it shows whether the pipeline opened up or is pinned
        # at the floor by backpressure.  pid label: gauges resolve
        # most-recent-wins per series, so processes sharing a label set
        # would flap among unrelated windows; distinct series per
        # submitter (bounded by the GCS's stale-reporter sweep).
        windows = [k.window for k in self._keys.values()]
        if windows:
            wlab = {**lab, "pid": str(os.getpid())}
            out.append(row("ray_tpu_submit_window_max", max(windows),
                           "gauge", labels=wlab))
            out.append(row("ray_tpu_submit_window_mean",
                           sum(windows) / len(windows), "gauge",
                           labels=wlab))
        return out

    def _run(self, coro, timeout=None):
        """Run a coroutine from a sync caller thread."""
        if self.loop is None:
            raise RuntimeError("core worker not started")
        if threading.current_thread() is self._loop_thread or (
                self._loop_thread is None
                and threading.current_thread().name == "MainThread"
                and self.mode == "worker"):
            raise RuntimeError(
                "sync API called from the event-loop thread; use `await` "
                "inside async actors")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # ------------------------------------------------------- ref plumbing ---
    def _ref_factory(self, object_id: bytes, owner_addr):
        ref = ObjectRef(object_id, owner_addr, worker=self)
        if owner_addr and tuple(owner_addr) != self.address:
            # Deserializing someone else's ref makes this process a borrower
            # (reference: reference_count.cc borrower registration; here an
            # eager borrow_add to the owner, released on local GC).
            epoch = self.reference_counter.mark_borrowed(object_id,
                                                         tuple(owner_addr))
            if epoch is not None:
                self._notify_owner(tuple(owner_addr), "borrow_add", object_id,
                                   epoch=epoch)
        return ref

    def _ref_serialized_hook(self, ref: ObjectRef):
        ctx = get_context()
        owner = ref.owner_address
        remote = None if (owner is None or tuple(owner) == self.address) \
            else tuple(owner)
        captured = ctx.capture
        if captured is not None:
            # Containment capture: the surrounding put/arg/return records
            # the pin against the container's lifetime.
            captured.append((ref.binary(), remote))
        elif remote is None:
            # Out-of-band pickle of an owned ref: permanent escape pin.
            self.reference_counter.add_escape_pin(ref.binary())
        else:
            self._notify_owner(remote, "escape_pin", ref.binary())

    def _note_device_resident(self, oid: bytes, owner) -> None:
        """A get() on this worker just re-uploaded the object's arrays
        onto OUR accelerators: register this node in the owner's
        DEVICE-TIER replica directory so locality scheduling scores
        future consumers of the ref toward this slice (above any peer
        whose copy is host-arena bytes)."""
        from . import device_plane
        n, _b = device_plane.take_rebuilt_notice()
        if not n:
            return
        if owner is None or tuple(owner) == self.address:
            self.memory_store.add_location(
                oid, self.agent_address, device=True)
        else:
            self._notify_owner(tuple(owner), "object_location_add", oid,
                               addr=list(self.agent_address), dev=True)

    def _notify_owner(self, owner: tuple, method: str, object_id: bytes,
                      **extra):
        """Fire-and-forget refcount message to an object's owner; safe from
        any thread (GC runs __del__ wherever it likes)."""
        if self.loop is None or self._shutdown:
            return

        async def _go():
            try:
                conn = await self._peer_owner(owner)
                conn.notify(method, {"object_id": object_id,
                                     "worker_id": self.worker_id, **extra})
            except Exception:
                pass

        try:
            asyncio.run_coroutine_threadsafe(_go(), self.loop)
        except RuntimeError:
            pass

    def _on_ref_zero(self, object_id: bytes, owner_addr=None,
                     borrow_epoch: int = 0):
        if owner_addr is not None:
            # Borrowed ref fully dropped: release our borrow with the owner.
            self.memory_store.delete(object_id)
            self._notify_owner(tuple(owner_addr), "borrow_release", object_id,
                               epoch=borrow_epoch)
            return
        # Owned object freed: cascade containment pins, then free the
        # primary copy.
        self._release_nested(self.reference_counter.pop_contained(object_id))
        entry = self.memory_store.get(object_id)
        self.memory_store.delete(object_id)
        if entry is not None and entry.plasma_node is not None:
            node = tuple(entry.plasma_node)
            secondaries = tuple(entry.secondaries or ())
            if self.loop and not self._shutdown:
                asyncio.run_coroutine_threadsafe(
                    self._free_plasma(node, object_id, secondaries),
                    self.loop)

    async def _free_plasma(self, agent_addr, object_id: bytes,
                           secondaries=()):
        # Replica copies free in parallel with the primary: secondaries
        # are unpinned caches (the holder may have evicted already —
        # free is idempotent), but an explicit free bounds how long
        # freed bytes linger cluster-wide.
        for sec in secondaries:
            if tuple(sec) != tuple(agent_addr):
                rpc.spawn(self._free_secondary(tuple(sec), object_id))
        try:
            conn = self.agent if agent_addr == self.agent_address else \
                await self._peer_owner(agent_addr)
            await conn.call("free_objects", {"object_ids": [object_id]})
            return
        except rpc.RpcError:
            pass
        # The recorded primary node is unreachable (e.g. it finished a
        # graceful drain and this owner never re-read the object, so
        # plasma_node was never repointed): follow the drain's relocation
        # record so the adopted pinned copy — and the KV record itself,
        # cleared by the adoptive agent's free — can't leak.
        try:
            moved = await self._migrated_location(object_id)
            if moved is not None and tuple(moved) != tuple(agent_addr):
                conn = await self._peer_owner(tuple(moved))
                await conn.call("free_objects", {"object_ids": [object_id]})
        except rpc.RpcError:
            pass

    async def _free_secondary(self, addr: tuple, object_id: bytes) -> None:
        try:
            conn = self.agent if addr == self.agent_address else \
                await self._peer_owner(addr)
            await conn.call("free_objects", {"object_ids": [object_id]})
        except (rpc.RpcError, asyncio.TimeoutError):
            pass    # evictable cache: the holder's sweep also cleans up

    async def _peer_owner(self, addr) -> rpc.Connection:
        addr = tuple(addr)
        conn = self._owner_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(addr, name="cw->peer", retries=3)
            self._owner_conns[addr] = conn
        return conn

    # ------------------------------------------------------------- put/get --
    def put(self, value: Any) -> ObjectRef:
        """Serialize ONCE on the calling thread (also keeps multi-GB
        pickling off the event loop); small ref-free values then complete
        entirely here — a freshly minted id can have no waiters, plasma
        isn't touched, and the serialization capture is thread-local.

        Large values ALSO complete on the calling thread: the pickle-5
        parts are written straight into the shm arena in one native iov
        memcpy (GIL released), so the event loop never carries the copy
        and a put costs exactly one memory pass (reference: plasma's
        create/write-in-place/seal discipline — the Cython put path
        likewise copies on the caller).  Only the arena-full fallback
        (spill backpressure) routes through the loop."""
        from . import device_plane
        ctx = get_context()
        ctx.capture = captured = []
        device_plane.take_staged_notice()       # drain stale counts
        try:
            parts = ctx.serialize(value)
        finally:
            ctx.capture = None
        # A value containing device arrays registers this node in the
        # ref's DEVICE-TIER directory: the arrays stay resident in this
        # process, so consumers scheduled here skip the re-upload.
        staged_dev = device_plane.take_staged_notice()
        size = ctx.total_size(parts)
        cfg = get_config()
        if not captured and size <= self._inline_limit \
                and cfg.put_small_object_in_memory_store:
            oid = self._next_put_id()
            self.reference_counter.add_owned(oid)
            self.memory_store.put_inline(oid, protocol.concat_parts(parts))
            if staged_dev:
                self.memory_store.add_location(
                    oid, self.agent_address, device=True)
            return ObjectRef(oid, self.address, worker=self)
        if size > self._inline_limit and not self._on_loop_thread():
            # Zero-copy sync plasma path (containment bookkeeping is
            # thread-safe; _record_contained pins before the store).
            # Loop-thread callers fall through to _run, which raises the
            # same "use await / put_async" guard as before — with no
            # ownership state recorded and no multi-GB memcpy blocking
            # the event loop.
            oid = self._next_put_id()
            self.reference_counter.add_owned(oid)
            self._record_contained(oid, captured)
            if self._put_store_sync(oid, parts):
                self.memory_store.put_plasma_location(
                    oid, list(self.agent_address), size=size)
                if staged_dev:
                    self.memory_store.add_location(
                        oid, self.agent_address, device=True)
                return ObjectRef(oid, self.address, worker=self)
            # Arena full: loop-side backpressure/spill.  _run blocks this
            # thread until stored, so the caller may mutate its buffers
            # (which `parts` still views) only after the copy completes.
            ref = self._run(self._put_plasma_prepinned(oid, parts))
        else:
            ref = self._run(
                self._put_serialized_async(parts, captured, size))
        if staged_dev:
            self.memory_store.add_location(
                ref.binary(), self.agent_address, device=True)
        return ref

    def _put_store_sync(self, oid: bytes, parts) -> bool:
        """One native create+iov-copy+seal into shm on the CALLING thread,
        keeping the writer pin; the pin-transfer notify is posted to the
        loop (mailbox order guarantees it precedes any later free of the
        same id).  False when the arena is full — caller takes the
        backpressure path."""
        try:
            self.store.put(oid, parts, keep_pin=True)
        except StoreFullError:
            return False
        if self._on_loop_thread():
            self._send_pin_transfer(oid)
        else:
            self._post_to_loop(lambda: self._send_pin_transfer(oid))
        return True

    async def _put_plasma_prepinned(self, oid: bytes, parts) -> ObjectRef:
        """Finish a sync put whose fast path hit a full arena (ownership
        already recorded)."""
        await self._put_plasma(oid, parts)
        return ObjectRef(oid, self.address, worker=self)

    async def _put_serialized_async(self, parts, captured, size
                                    ) -> ObjectRef:
        oid = self._next_put_id()
        self.reference_counter.add_owned(oid)
        self._record_contained(oid, captured)
        cfg = get_config()
        if size <= self._inline_limit and cfg.put_small_object_in_memory_store:
            self.memory_store.put_inline(oid, protocol.concat_parts(parts))
        else:
            await self._put_plasma(oid, parts)
        return ObjectRef(oid, self.address, worker=self)

    async def put_async(self, value: Any) -> ObjectRef:
        ctx = get_context()
        ctx.capture = captured = []
        try:
            parts = ctx.serialize(value)
        finally:
            ctx.capture = None
        return await self._put_serialized_async(
            parts, captured, ctx.total_size(parts))

    def _next_put_id(self) -> bytes:
        # Minted from the driver thread (submit_actor_task) and the loop
        # thread (put/_store_big_puts) alike: always under the lock.
        with self._seq_lock:
            self._put_counter += 1
            idx = self._put_counter
        task = self.current_task_id
        if not task:
            # put() outside any executing task (e.g. a user thread inside
            # an actor, like a Tune trial's trainable thread): owned by a
            # per-process pseudo-task so ids stay well-formed.
            if self._process_task_id_cache is None:
                self._process_task_id_cache = TaskID.for_normal_task(
                    JobID(self.job_id or b"\x00\x00\x00\x00")).binary()
            task = self._process_task_id_cache
        return ObjectID.for_put(TaskID(task), idx).binary()


    def _record_contained(self, container_id: bytes, captured,
                          take_pins: bool = True):
        """Pin refs nested inside a value until the container is freed.
        take_pins=True when THIS process just serialized the value (we take
        the pins: sync for our own objects — race-free — and an ordered
        escape_pin notify for remote owners, which lands before any
        borrow_release we might later send on the same connection).
        take_pins=False when the pins were already taken by the serializing
        worker and the reply merely transfers release responsibility."""
        if not captured:
            return
        self.reference_counter.add_contained(container_id, captured)
        if take_pins:
            for noid, nowner in captured:
                if nowner is None:
                    self.reference_counter.add_escape_pin(noid)
                else:
                    self._notify_owner(nowner, "escape_pin", noid)

    # Loop-offload threshold for the arena memcpy inside
    # store_with_backpressure (below it the executor hop costs more).
    _OFFLOAD_COPY_MIN = 4 * 1024 * 1024

    async def _put_plasma(self, oid: bytes, parts):
        size = get_context().total_size(parts)
        await self.store_with_backpressure(oid, parts)
        self.memory_store.put_plasma_location(
            oid, list(self.agent_address), size=size)

    async def store_with_backpressure(self, oid: bytes, parts,
                                      owner_addr=None):
        """Create-queue backpressure (reference: plasma create_request_queue):
        on ENOMEM, ask the agent to spill pinned primaries and retry; an
        object that can never fit the arena spills straight to disk. Shared
        by puts and large task returns.

        `owner_addr` names the object's OWNER for the agent's pin records
        (drain migration tells the owner's replica directory where the
        primary moved): task returns are owned by the CALLER, so the
        executing worker passes the caller's address; puts default to
        this process.

        Pin transfer: the shm put keeps the writer's refcount and hands it
        to the agent with a one-way pin_transfer notify — the object is
        never evictable between seal and the agent's pin bookkeeping (the
        old blocking pin_object round trip had exactly that window, and
        cost a full RPC latency per large put)."""
        size = get_context().total_size(parts)
        cfg = get_config()
        deadline = time.monotonic() + cfg.create_backpressure_timeout_s
        stored = False
        refusal = None        # typed refusal from the admission queue

        def _try_store() -> bool:
            try:
                self.store.put(oid, parts, keep_pin=True)
                return True
            except StoreFullError:
                return False

        loop = asyncio.get_running_loop()
        try:
            oversized = size >= self.store.stats()["capacity"] // 2
        except Exception:
            oversized = False
        while True:
            # Multi-MB copies run on an executor thread so this (worker /
            # driver) loop keeps serving RPC during the memcpy; small ones
            # stay inline — the thread hop costs more than the copy.
            if size >= self._OFFLOAD_COPY_MIN and self.executor is not None:
                ok = await loop.run_in_executor(self.executor, _try_store)
            else:
                ok = _try_store()
            if ok:
                stored = True
                self._send_pin_transfer(oid, owner_addr)
                break
            if oversized:
                break  # can never (usefully) fit: straight to the disk tier
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            # Admission queue (the CreateRequestQueue analogue): the
            # agent reserves headroom — parking us FIFO while its
            # eviction/spill sweeps make room — or refuses TYPED with a
            # retry_after_s hint.  The reservation is atomic under the
            # queue, so a racing put can't steal the freed headroom and
            # the sweep won't reclaim our in-progress region.
            try:
                res = await self.agent.call(
                    "reserve_create",
                    {"object_id": oid, "nbytes": size,
                     "timeout_s": remaining},
                    timeout=remaining + 30.0)
            except rpc.RpcError:
                res = None     # reconnect blip: legacy spill-and-retry
            if isinstance(res, dict) and not res.get("ok"):
                refusal = res
                break          # deadline/queue-full: try the disk tier
            if res is None:
                try:
                    freed = (await self.agent.call(
                        "ensure_space", {"nbytes": size}))["freed"]
                except rpc.RpcError:
                    freed = 0
                if freed == 0:
                    if time.monotonic() >= deadline:
                        break
                    await asyncio.sleep(0.05)
        if not stored:
            # Worker and agent share the host: write the spill file here
            # (off-loop) and just register it — no copy crosses the RPC.
            retry_after = float((refusal or {}).get("retry_after_s", 1.0))
            try:
                path = await self.agent.call("spill_path",
                                             {"object_id": oid})

                def _write():
                    with open(path, "wb") as f:
                        for p in parts:
                            f.write(p)

                await asyncio.get_running_loop().run_in_executor(
                    self.executor, _write)
                registered = await self.agent.call(
                    "spill_register",
                    {"object_id": oid,
                     "owner_addr": list(owner_addr or self.address)},
                    timeout=60)
            except (rpc.RpcError, OSError) as e:
                # NEVER a raw arena/IO exception out of a put: the typed
                # error carries the backoff hint and keeps accounting
                # intact (no reservation, no pin, no partial region).
                raise exc.ObjectStoreFullError(
                    f"object of size {size} does not fit and the spill "
                    f"tier failed ({e})", retry_after_s=retry_after) from e
            if not registered:
                raise exc.ObjectStoreFullError(
                    f"object of size {size} does not fit and could not "
                    f"spill", retry_after_s=retry_after)
            # Disk-spilled primaries carry no shm refcount; the agent still
            # records the owner pin so free_objects accounting matches.
            self._send_pin_transfer(oid, owner_addr)

    def _send_pin_transfer(self, oid: bytes, owner_addr=None) -> None:
        """Hand the writer-held pin to the agent. Normally a one-way notify
        on the agent connection (ordered ahead of any later free). If the
        connection is down the notify raises synchronously — release our
        pin and let the reconnect path re-pin with a blocking pin_object.
        An asynchronous loss (frame written, agent died before processing)
        is node death: the arena dies with the agent, so a leaked refcount
        in it is moot (workers watching the agent connection exit too)."""
        try:
            self.agent.notify("pin_transfer", {
                "object_id": oid,
                "owner_addr": list(owner_addr or self.address)})
        except rpc.RpcError:
            self.store.release(oid)
            rpc.spawn(self._pin_after_reconnect(oid, owner_addr))

    async def _pin_after_reconnect(self, oid: bytes,
                                   owner_addr=None) -> None:
        try:
            await self.agent.call("pin_object", {
                "object_id": oid,
                "owner_addr": list(owner_addr or self.address)})
        except rpc.RpcError:
            pass

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(
                f"get() accepts ObjectRef or a list of ObjectRefs; got "
                f"{type(bad[0]).__name__}")
        release = self._maybe_release_cpu(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            if single:
                hit, value = self._try_get_sync(refs[0], timeout)
                if hit:
                    return value
                # Fallback continues on the SAME deadline — the sync wait
                # above already consumed part of the caller's budget.
                if deadline is not None:
                    timeout = max(0.0, deadline - time.monotonic())
            values = self._run(self._get_many(refs, timeout))
        finally:
            if release:
                self._notify_agent_blocked(False)
        return values[0] if single else values

    def _try_get_sync(self, ref: ObjectRef, timeout) -> Tuple[bool, Any]:
        """Loop-free get of ONE owned inline object: park the calling
        thread on a concurrent future the memory store resolves directly
        from its _wake — no call_soon_threadsafe wake, no Task, no gather
        (reference: the Cython get blocks on a C++ future; this is the
        Python-plane equivalent of that zero-loop hop).  Returns
        (False, None) to fall back for anything needing loop IO (plasma
        reads, borrowed refs, recovery)."""
        if self._on_loop_thread():
            return False, None           # must not block the loop
        owner = ref.owner_address
        if owner is not None and tuple(owner) != self.address:
            return False, None           # borrowed: owner RPC path
        ms = self.memory_store
        oid = ref.binary()
        entry = ms.get(oid)
        if entry is None:
            fut = ms.add_sync_waiter(oid)
            if fut is not None:
                try:
                    fut.result(timeout)
                except concurrent.futures.TimeoutError:
                    ms.discard_sync_waiter(oid, fut)
                    raise exc.GetTimeoutError(
                        f"timed out getting {oid.hex()}") from None
            entry = ms.get(oid)
        if entry is None or entry.data is None:
            return False, None           # plasma-resident: loop IO path
        from . import device_plane
        device_plane.take_rebuilt_notice()      # drain stale counts
        value = get_context().deserialize(memoryview(entry.data))
        if isinstance(value, exc.RayError):
            raise value
        self._note_device_resident(oid, None)   # owner-local fast path
        return True, value

    def _maybe_release_cpu(self, refs) -> bool:
        """In-task blocking get/wait on an executor thread: tell the agent
        to free this lease's CPU while we wait (reference:
        NotifyDirectCallTaskBlocked).  Only when some ref isn't already
        local — an all-hit get never round-trips the agent."""
        if not getattr(task_exec_tls, "active", False):
            return False
        if all(self.memory_store.contains(r.binary()) for r in refs):
            return False
        return self._notify_agent_blocked(True)

    def _notify_agent_blocked(self, blocked: bool) -> bool:
        agent = getattr(self, "agent", None)
        if agent is None or agent.closed:
            return False
        method = "worker_blocked" if blocked else "worker_unblocked"
        try:
            asyncio.run_coroutine_threadsafe(
                agent.call(method, {"worker_id": self.worker_id}), self.loop)
        except RuntimeError:          # loop shutting down
            return False
        return True

    async def get_async(self, ref: ObjectRef, timeout=None):
        return (await self._get_many([ref], timeout))[0]

    def get_future(self, ref: ObjectRef):
        return asyncio.run_coroutine_threadsafe(
            self._get_many([ref], None), self.loop)

    async def _get_many(self, refs: List[ObjectRef], timeout):
        deadline = None if timeout is None else time.monotonic() + timeout
        # A get() inside a deadline-carrying task is bounded by the
        # task's REMAINING budget even with no explicit timeout — the
        # fetch must not outlive the promise its caller made.
        amb = deadlines.remaining()
        if amb is not None:
            amb_deadline = time.monotonic() + amb
            if deadline is None or amb_deadline < deadline:
                try:
                    return await self._get_many_at(refs, amb_deadline)
                except exc.GetTimeoutError as e:
                    raise exc.DeadlineExceededError(
                        f"get() exceeded the task's end-to-end deadline: "
                        f"{e}") from None
        return await self._get_many_at(refs, deadline)

    async def _get_many_at(self, refs: List[ObjectRef], deadline):
        if len(refs) < 4:
            return await asyncio.gather(
                *[self._get_one(r, deadline) for r in refs])
        # Batched fast path: every OWNED object is tracked in the memory
        # store (inline puts, plasma puts via _put_plasma, task returns via
        # _handle_reply), so one wait_for_many future covers all pending
        # owned refs — instead of a Task+Event per ref, which dominates
        # caller-side CPU under fan-out (reference: memory_store.cc GetAsync
        # registers N callbacks on one request context for the same reason).
        self_addr = self.address
        mstore = self.memory_store
        ctx = get_context()
        objects = mstore._objects
        pending = None
        owned = [r.owner_address is None or tuple(r.owner_address) == self_addr
                 for r in refs]
        for r, own in zip(refs, owned):
            if own:
                entry = objects.get(r.binary())
                if entry is None:
                    if pending is None:
                        pending = []
                    pending.append(r.binary())
                elif entry.is_exception:
                    # Raise an already-stored error before waiting on
                    # anything else (gather's first-error semantics).
                    raise ctx.deserialize(memoryview(entry.data))
        if pending:
            remaining = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            if not await mstore.wait_for_many(pending, remaining):
                raise exc.GetTimeoutError(
                    f"timed out getting {len(pending)} pending objects")
            # wait_for_many also completes EARLY when any waited-on entry
            # lands as an error: surface it now rather than decoding/IO-ing
            # the rest first (stragglers would otherwise block below).
            for oid in pending:
                entry = objects.get(oid)
                if entry is not None and entry.is_exception:
                    raise ctx.deserialize(memoryview(entry.data))
        # Inline entries decode in place; anything needing IO (plasma reads,
        # borrowed refs, recovery) keeps the concurrent per-ref path.
        out = [None] * len(refs)
        io_idx = None
        for i, r in enumerate(refs):
            entry = objects.get(r.binary()) if owned[i] else None
            if entry is not None and entry.data is not None:
                value = ctx.deserialize(memoryview(entry.data))
                if isinstance(value, exc.RayError):
                    raise value
                out[i] = value
            else:
                if io_idx is None:
                    io_idx = []
                io_idx.append(i)
        if io_idx:
            vals = await asyncio.gather(
                *[self._get_one(refs[i], deadline) for i in io_idx])
            for i, v in zip(io_idx, vals):
                out[i] = v
        return out

    async def _get_one(self, ref: ObjectRef, deadline):
        from . import device_plane
        data = await self._fetch_serialized(ref, deadline)
        device_plane.take_rebuilt_notice()      # drain stale counts
        value = get_context().deserialize(data)
        if isinstance(value, exc.RayError):
            raise value
        self._note_device_resident(ref.binary(), ref.owner_address)
        return value

    def _pinned(self, oid: bytes, view: memoryview) -> memoryview:
        """Tie a store.get read pin's lifetime to the VALUE built over it.

        Deserialize is zero-copy: the user's arrays alias the arena
        mapping, so the pin must outlive them — but it must not outlive
        them FOREVER.  Re-exporting the view through a numpy base whose
        collection releases the pin makes every downstream slice (pickle5
        buffers, ndarray views) keep the base alive; when the last one
        dies, the pin returns and the bytes become evictable/spillable
        again.  Without this, each driver get and worker arg read leaked
        one pin per object for the life of the process — under sustained
        arena oversubscription the resident set only ever grew, and every
        later put aged out its full admission deadline before reaching
        the disk tier."""
        import numpy as np
        base = np.frombuffer(view, np.uint8)
        weakref.finalize(base, _release_read_pin, self.store, oid)
        return memoryview(base).toreadonly()

    async def _fetch_serialized(self, ref: ObjectRef, deadline) -> memoryview:
        oid = ref.binary()
        owner = ref.owner_address or self.address
        recoveries = 0
        while True:
            # 1. Local memory store (owned objects / cached results).
            entry = self.memory_store.get(oid)
            if entry is not None:
                if entry.data is not None:
                    return memoryview(entry.data)
                try:
                    return await self._read_plasma(
                        oid, entry.plasma_node, deadline,
                        locations=self._ordered_locations(entry),
                        owner_addr=list(self.address))
                except exc.ObjectLostError:
                    # Primary copy gone (node death / eviction): owners
                    # re-execute the creating task from lineage (reference:
                    # object_recovery_manager.h:41). Bounded by the caller's
                    # get() deadline.
                    if tuple(owner) == self.address and recoveries < 3:
                        remaining = None if deadline is None else \
                            deadline - time.monotonic()
                        if remaining is not None and remaining <= 0:
                            raise exc.GetTimeoutError(
                                f"timed out getting {oid.hex()}") from None
                        try:
                            ok = await asyncio.wait_for(
                                self._recover_object(oid), remaining)
                        except asyncio.TimeoutError:
                            raise exc.GetTimeoutError(
                                f"timed out recovering {oid.hex()}") from None
                        if ok:
                            recoveries += 1
                            continue
                    raise
            # 2. Local shared memory.
            view = self.store.get(oid, timeout_ms=0)
            if view is not None:
                # Zero-copy; the read pin releases when the deserialized
                # value is collected (_pinned).
                return self._pinned(oid, view)
            # 3. Owner-mediated resolution.
            if tuple(owner) == self.address:
                timeout = None if deadline is None else max(
                    0.0, deadline - time.monotonic())
                entry = await self.memory_store.wait_for(oid, timeout)
                if entry is None:
                    raise exc.GetTimeoutError(f"timed out getting {oid.hex()}")
                continue
            conn = await self._peer_owner(owner)
            timeout_ms = -1 if deadline is None else int(
                max(0.0, deadline - time.monotonic()) * 1000)
            try:
                # timeout=0 opts out of the control-call default: an
                # unbounded ray.get() long-polls the owner by design
                # (the 30s-slice wait lives server-side).
                res = await conn.call(
                    "get_object", {"object_id": oid, "timeout_ms": timeout_ms},
                    timeout=0 if deadline is None else
                    max(0.1, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                raise exc.GetTimeoutError(f"timed out getting {oid.hex()}")
            except rpc.ConnectionLost:
                raise exc.OwnerDiedError(
                    f"owner {owner} of {oid.hex()} is unreachable")
            if res is None:
                raise exc.GetTimeoutError(f"timed out getting {oid.hex()}")
            if "inline" in res:
                return memoryview(res["inline"])
            try:
                return await self._read_plasma(
                    oid, res["plasma"], deadline,
                    locations=res.get("locations"),
                    owner_addr=list(owner))
            except exc.ObjectLostError:
                # Borrowers can't reconstruct; ask the owner to. Bounded by
                # the caller's get() deadline.
                if recoveries < 3:
                    recoveries += 1
                    remaining = 130.0 if deadline is None else \
                        min(130.0, deadline - time.monotonic())
                    if remaining <= 0:
                        raise exc.GetTimeoutError(
                            f"timed out getting {oid.hex()}") from None
                    try:
                        if await conn.call("recover_object",
                                           {"object_id": oid},
                                           timeout=remaining):
                            continue
                    except (rpc.RpcError, asyncio.TimeoutError):
                        pass
                raise

    async def _migrated_location(self, oid: bytes):
        """Where a graceful drain republished this object's primary copy,
        per the GCS KV record the draining agent left (ns 'migrated'), or
        None.  Lets owners repoint instead of re-executing lineage — and
        covers put objects, which have no lineage at all."""
        try:
            v = await self.gcs.call(
                "kv_get", {"ns": "migrated", "key": oid.hex()}, timeout=10)
        except (rpc.RpcError, asyncio.TimeoutError):
            return None
        if not v:
            return None
        import json
        try:
            host, port = json.loads(
                v.decode() if isinstance(v, (bytes, bytearray)) else v)
            return (host, int(port))
        except (ValueError, TypeError):
            return None

    async def _recover_object(self, oid: bytes) -> bool:
        """Restore a lost object: probe the recorded primary, follow a
        drain-migrated copy, restore from the durable spill tier, and only
        then re-execute the creating task from lineage (reference:
        task_manager.h:227 ResubmitTask + object_recovery_manager.cc).
        Deduped across concurrent losses of the same id; actor task returns
        carry no lineage and are never replayed (side effects)."""
        existing = self._recovering.get(oid)
        if existing is not None:
            return await asyncio.shield(existing)
        fut = asyncio.get_running_loop().create_future()
        self._recovering[oid] = fut
        ok = False
        try:
            ok = await self._recover_object_inner(oid)
            return ok
        finally:
            if not fut.done():
                fut.set_result(ok)
            self._recovering.pop(oid, None)

    async def _recover_object_inner(self, oid: bytes) -> bool:
        # Probe first: a transient pull failure must not trigger a
        # destructive re-execution (tasks may have side effects and a
        # failed rerun would overwrite healthy sibling returns).  The
        # probe walks the LOCATION SET — primary first, then every
        # registered secondary: a dead primary with a live replica is a
        # repoint (promote the survivor), never a reconstruction.
        entry = self.memory_store.get(oid)
        if entry is not None and entry.plasma_node is not None:
            if await self._primary_alive(oid, tuple(entry.plasma_node)):
                return True
            for sec in list(entry.secondaries or ()):
                if not await self._primary_alive(oid, tuple(sec)):
                    self.memory_store.remove_location(oid, sec)
                    continue
                # A secondary survives the primary's loss: promote it.
                # adopt_primary pins the (already-present) copy so the
                # new primary can't be LRU-evicted from under us; if the
                # pin fails (copy evicted mid-probe) keep probing.
                try:
                    conn = await self._peer_owner(tuple(sec))
                    if await conn.call("adopt_primary", {
                            "object_id": oid,
                            "from_addrs": [list(sec)],
                            "owner_addr": list(self.address),
                            "priority": 0}, timeout=30):
                        entry.plasma_node = list(sec)
                        self.memory_store.remove_location(oid, sec)
                        return True
                except (rpc.RpcError, asyncio.TimeoutError):
                    pass
                self.memory_store.remove_location(oid, sec)
            # Storage-tier fast path: a registered disk holder still has
            # the bytes in its spill file even though its arena copy is
            # gone.  Ask that agent to restore direct-to-arena
            # (read_file_into), pin the restored copy, and repoint the
            # primary there — no lineage re-execution.
            for dsk in list(entry.disk_nodes or ()):
                try:
                    conn = await self._peer_owner(tuple(dsk))
                    if await conn.call("restore_object",
                                       {"object_id": oid}, timeout=60):
                        await conn.call("pin_object", {
                            "object_id": oid,
                            "owner_addr": list(self.address)}, timeout=30)
                        entry.plasma_node = list(dsk)
                        self.memory_store.remove_location(oid, dsk,
                                                          disk=True)
                        return True
                except (rpc.RpcError, asyncio.TimeoutError):
                    pass
                self.memory_store.remove_location(oid, dsk, disk=True)
        # Drain-migration fast path: a gracefully drained node republished
        # its sole primaries to a peer before exiting — repoint the
        # owner's location record and read from the new holder; no
        # reconstruction, no side effects.
        moved = await self._migrated_location(oid)
        if moved is not None and await self._primary_alive(oid, moved):
            if entry is not None:
                entry.plasma_node = list(moved)
            return True
        # Cloud-spill fast path: if a durable external copy was
        # registered (object_spill_external_uri), the LOCAL agent can
        # restore it — no destructive lineage re-execution, and it
        # works even when the spiller node is dead (reference:
        # spilled-object URLs usable cluster-wide,
        # external_storage.py).
        try:
            if await self.agent.call("restore_object",
                                     {"object_id": oid}, timeout=60):
                # The local agent is the new primary: re-pin there
                # and repoint the owner's location record.
                await self.agent.call("pin_object", {
                    "object_id": oid,
                    "owner_addr": list(self.address)})
                if entry is not None:
                    entry.plasma_node = self.agent_address
                return True
        except (rpc.RpcError, asyncio.TimeoutError):
            pass
        spec = self.reference_counter.get_lineage(oid)
        if spec is None:
            return False
        # Resubmission can only succeed if its by-reference args are
        # still resolvable (live somewhere, or themselves recoverable).
        for e in spec["args"]:
            if "ref" not in e:
                continue
            aid = bytes(e["ref"][0])
            aowner = tuple(e["ref"][1])
            if aowner == self.address and \
                    not self.memory_store.contains(aid) and \
                    not self.store.contains(aid) and \
                    self.reference_counter.get_lineage(aid) is None:
                return False
        self.memory_store.delete(oid)  # only the lost return
        respec = dict(spec)
        # Ensure at least one attempt; negative stays negative (infinite).
        rl = respec.get("retries_left", 0)
        respec["retries_left"] = rl if rl < 0 else max(rl, 1)
        key = protocol.scheduling_key(respec["fn_id"], respec["resources"],
                                      respec.get("scheduling_strategy"),
                                      respec.get("runtime_env"))
        state = self._keys.get(key)
        if state is None:
            state = self._keys[key] = _KeyState(
                respec["resources"], respec.get("scheduling_strategy"),
                respec.get("runtime_env"))
        state.queue.append(_PendingTask(respec, []))
        self._pump(key, state)
        entry = await self.memory_store.wait_for(oid, 120)
        return entry is not None

    async def _primary_alive(self, oid: bytes, agent_addr: tuple) -> bool:
        """Short-timeout probe of the agent recorded as holding the primary."""
        if agent_addr == self.agent_address:
            if self.store.contains(oid):
                return True
            try:
                return bool(await self.agent.call(
                    "object_info", {"object_id": oid}, timeout=5))
            except (rpc.RpcError, asyncio.TimeoutError):
                return False
        try:
            conn = await self._peer_owner(agent_addr)
            return bool(await conn.call("object_info", {"object_id": oid},
                                        timeout=5))
        except (rpc.RpcError, asyncio.TimeoutError):
            return False

    async def _read_plasma(self, oid: bytes, agent_addr, deadline,
                           locations=None, owner_addr=None) -> memoryview:
        """`locations` is the owner's full replica set (primary first;
        falls back to just `agent_addr`): the local agent stripes/hedges
        the pull across every holder and — given `owner_addr` — registers
        itself as a fresh secondary so the NEXT puller has one more
        source (receiver-becomes-source broadcast)."""
        view = self.store.get(oid, timeout_ms=0)
        if view is not None:
            return self._pinned(oid, view)
        if tuple(agent_addr) == self.agent_address:
            # Spilled primaries restore on demand (reference: raylet
            # RestoreSpilledObject on the get path).  Bounded retry: a
            # restore can succeed (or report already-in-store) and the
            # object be EVICTED again before this process maps it — under
            # memory pressure with concurrent restores the window is
            # real, and without the retry the tail wait below can never
            # bring the object back (nothing re-restores it).
            for _ in range(4):
                if await self.agent.call("restore_object",
                                         {"object_id": oid}, timeout=120):
                    view = self.store.get(oid, timeout_ms=0)
                    if view is not None:
                        return self._pinned(oid, view)
                    continue
                break
            # Restore failed — or succeeded 4x with the copy evicted (and
            # re-spilled) before this process mapped it.  Either way the
            # spill file is the durable copy: read it directly.
            spilled = await self._read_spilled(self.agent, oid)
            if spilled is not None:
                return spilled
            timeout_ms = 5_000 if deadline is None else int(
                min(5.0, max(0.0, deadline - time.monotonic())) * 1000)
            view = self.store.get(oid, timeout_ms=timeout_ms)
            if view is None:
                raise exc.ObjectLostError(f"{oid.hex()} not in local store")
            return self._pinned(oid, view)
        # Wall-clock deadline for the pull: the tighter of the caller's
        # get() bound (monotonic) and the ambient task deadline — carried
        # in the RPC frame and inside the payload so the agent bounds its
        # chunk fetches by the REMAINING budget.
        ambient_dl = deadlines.get()
        wall_dl = ambient_dl
        caller_dl = None
        if deadline is not None:
            caller_dl = time.time() + max(0.0, deadline - time.monotonic())
            wall_dl = caller_dl if wall_dl is None \
                else min(wall_dl, caller_dl)

        def _pull_deadline_exc(msg: str) -> Exception:
            # When the caller's get(timeout=) bound is the binding
            # constraint (no tighter ambient task deadline), an expiry
            # is the documented caller-local outcome — GetTimeoutError,
            # on which poll loops legitimately continue — never the
            # end-to-end DeadlineExceededError contract.
            if caller_dl is not None and (ambient_dl is None
                                          or caller_dl <= ambient_dl):
                return exc.GetTimeoutError(
                    f"timed out pulling {oid.hex()}")
            return exc.DeadlineExceededError(msg)

        from_addrs = [list(a) for a in (locations or [])] \
            or [list(agent_addr)]
        ok = False
        for pull_attempt in range(2):
            try:
                ok = await self.agent.call("pull_object", {
                    "object_id": oid, "from_addrs": from_addrs,
                    "owner_addr": owner_addr,
                    "priority": 0, "deadline": wall_dl}, timeout=120,
                    deadline=wall_dl)
                break
            except exc.DeadlineExceededError as e:
                # Local deadline= bound on the call expired (blackholed
                # agent link) before any remote reply.
                raise _pull_deadline_exc(str(e)) from None
            except rpc.RemoteError as e:
                # The agent distinguishes "object gone at every source"
                # (ok=False -> ObjectLostError, recovery may engage) from
                # a TRANSIENT mid-stream failure (ObjectTransferError —
                # drops/timeouts on a live source).  Match the FIRST line
                # only: rpc dispatch formats remote errors as
                # "TypeName: message\n<traceback>", so a traceback that
                # merely mentions the type can't misclassify.  A
                # transient failure gets ONE in-place retry; failing
                # twice escalates to the lost path below — recovery
                # probes the primary first (_recover_object), so a
                # source that is alive but flaky is never destructively
                # re-executed, while a source that can never serve the
                # bytes (e.g. truncated spill file) does reach
                # reconstruction instead of erroring forever.
                first = str(e).split("\n", 1)[0]
                if first.startswith("DeadlineExceededError"):
                    # The pull's budget ran out at the agent: surface the
                    # typed deadline outcome — NOT ObjectLostError, which
                    # would trigger destructive lineage re-execution for
                    # an object that may be perfectly healthy.
                    raise _pull_deadline_exc(first) from None
                if first.startswith("ObjectTransferError") \
                        and pull_attempt == 0:
                    continue
                ok = False
                break
            except (rpc.RpcError, asyncio.TimeoutError):
                ok = False  # source unreachable == primary copy lost
                break
        if not ok:
            raise exc.ObjectLostError(f"failed to pull {oid.hex()}")
        if not self.store.contains(oid):
            # Pull landed on disk (arena pressure): restore, or read the
            # local spill file directly when it can never fit the arena.
            if not await self.agent.call("restore_object", {"object_id": oid},
                                         timeout=120):
                spilled = await self._read_spilled(self.agent, oid)
                if spilled is not None:
                    return spilled
        view = self.store.get(oid, timeout_ms=5000)
        if view is None:
            raise exc.ObjectLostError(f"{oid.hex()} pulled but not sealed")
        return self._pinned(oid, view)

    async def _read_spilled(self, agent_conn, oid: bytes):
        """Chunked read of a spilled object that cannot re-enter the arena
        (reference: spilled_object_reader.h — readers stream straight from
        the spill file).  Chunks arrive as raw out-of-band frames scattered
        directly into the destination buffer (no msgpack pass, no
        intermediate bytes), with a window of requests in flight to
        pipeline the agent's file reads under the wire."""
        info = await agent_conn.call("object_info",
                                     {"object_id": oid, "timeout_ms": 0})
        if info is None or not info.get("spilled"):
            return None
        size = info["size"]
        cfg = get_config()
        chunk = cfg.object_transfer_chunk_bytes
        out = bytearray(size)
        dest = memoryview(out)

        class _ChunkFailed(Exception):
            """Raised (not returned) so gather_windowed cancels the rest
            of the window — a failed first chunk of a multi-GB object
            must not let the remaining gigabytes transfer anyway."""

        async def fetch(pos: int) -> None:
            n = min(chunk, size - pos)
            res = await agent_conn.call_raw(
                "fetch_chunk",
                {"object_id": oid, "offset": pos, "length": n,
                 "raw": True},
                sink=dest[pos:pos + n], timeout=60)
            if isinstance(res, int) and res == n:
                return
            if isinstance(res, (bytes, bytearray)) and len(res) == n:
                dest[pos:pos + n] = res        # legacy peer
                return
            raise _ChunkFailed(pos)

        try:
            await rpc.gather_windowed(
                fetch, range(0, size, chunk),
                cfg.object_transfer_max_inflight_chunks)
        except _ChunkFailed:
            return None           # absent / gone marker / short read
        return dest

    # Owner-side service: borrowers resolve objects through us.
    async def h_get_object(self, conn, p):
        oid = p["object_id"]
        timeout_ms = p.get("timeout_ms", 0)
        timeout = None if timeout_ms < 0 else timeout_ms / 1000.0
        entry = await self.memory_store.wait_for(oid, timeout)
        if entry is None:
            return None
        if entry.data is not None:
            data = entry.data
            # Entries may hold bytes-like views (raw-frame landings);
            # normalize at the msgpack boundary only.
            return {"inline": data if isinstance(data, bytes)
                    else bytes(data)}
        # Full replica set rides along (primary first, suspects last) so
        # the borrower's pull stripes/hedges across every holder.
        return {"plasma": list(entry.plasma_node),
                "locations": self._ordered_locations(entry),
                "size": entry.size}

    async def h_free_notify(self, conn, p):
        for oid in p["object_ids"]:
            self.memory_store.delete(oid)
        return True

    # ------------------------------------------------ replica directory --
    # Owner-side location directory (reference: the ownership table tracks
    # every location of an object, Ownership NSDI'21 §4; here the owner IS
    # the directory).  Agents that complete a pull (or adopt a primary off
    # a draining node) register here; agents that evict/drop their copy
    # deregister; pullers query for the freshest holder set so a 1→N
    # broadcast stripes across every replica instead of serializing on the
    # primary's NIC.

    async def h_object_locations(self, conn, p):
        """Current holder set of an owned plasma object — primary first —
        plus its size.  None when the object is unknown/inline (inline
        objects travel through get_object, not the pull path).

        `add_addr` registers the CALLER as a (mid-pull) secondary in the
        same round trip, atomically on this owner's loop: N agents
        starting a broadcast pull concurrently each register-and-query,
        so all but the very first see their siblings and the stripe set
        forms immediately — the race that would otherwise leave every
        puller convoying on the primary."""
        oid = p["object_id"]
        entry = self.memory_store.get(oid)
        if entry is None or entry.plasma_node is None:
            return None
        if p.get("add_addr"):
            from .config import get_config
            self.memory_store.add_location(
                oid, tuple(p["add_addr"]),
                max_secondaries=get_config()
                .replica_directory_max_secondaries)
        # Exclude the caller from its own view (it can't pull from
        # itself) but keep everyone else, including other mid-pull
        # registrants.
        me = tuple(p["add_addr"]) if p.get("add_addr") else None
        return {"locations": [list(a) for a in self._ordered_locations(
                    entry) if tuple(a) != me],
                "size": entry.size}

    async def h_object_location_add(self, conn, p):
        """An agent holds (or is mid-pull of) a copy: record it.  With
        primary=True the primary record repoints — the drain path's
        adopt_primary uses this so owners learn the new pinned home
        without waiting for a recovery probe.  dev=True registers a
        DEVICE-TIER holder instead (a getter re-uploaded the object's
        arrays onto its accelerators): a locality-scheduling signal,
        never a pull source.  disk=True registers a STORAGE-TIER holder
        (the node spilled its copy to NVMe/external): a real restore
        source ranked below arena holders."""
        from .config import get_config
        return self.memory_store.add_location(
            p["object_id"], tuple(p["addr"]),
            primary=bool(p.get("primary")),
            device=bool(p.get("dev")),
            disk=bool(p.get("disk")),
            max_secondaries=get_config().replica_directory_max_secondaries)

    async def h_object_location_remove(self, conn, p):
        """A holder evicted/aborted its copy (or is draining): the
        directory entry must not outlive the bytes.  disk=True retracts
        only the storage-tier marking (the holder restored its spill
        file back into the arena — any arena record stands)."""
        self.memory_store.remove_location(p["object_id"], tuple(p["addr"]),
                                          disk=bool(p.get("disk")))
        return True

    def _ordered_locations(self, entry_or_oid) -> list:
        """Holder set of an owned object as wire addresses, primary first,
        gray-suspect/draining holders LAST (PR 4's health scores: a
        suspect node still serves, but the swarm prefers healthy
        sources).  Uses the cached node view only — never a GCS round
        trip on the read path."""
        entry = entry_or_oid if not isinstance(entry_or_oid, bytes) \
            else self.memory_store.get(entry_or_oid)
        if entry is None or entry.plasma_node is None:
            return []
        locs = entry.locations()
        if len(locs) > 1:
            locs = locs[:1] + self._suspects_last(locs[1:])
        return [list(a) for a in locs]

    def _suspects_last(self, addrs: list) -> list:
        """Stable-sort addresses: healthy targetable nodes first, then
        gray-suspect, then draining/unknown — per the (possibly stale)
        cached GCS view; on no view, order unchanged."""
        cached = getattr(self, "_nodes_cache", None)
        if not cached:
            return list(addrs)
        from . import scheduling_policy as policy
        rank = {}
        for n in cached[1]:
            rank[tuple(n["address"])] = (
                2 if not policy.targetable(n)
                else 1 if policy.suspicion_of(n) >= policy.SUSPECT_THRESHOLD
                else 0)
        return sorted(addrs, key=lambda a: rank.get(tuple(a), 0))

    # ----------------------------------------------------------------- wait --
    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        # timeout=0 polls return immediately — never worth the agent
        # round trip (and repeated release/reacquire churn).
        release = timeout != 0 and self._maybe_release_cpu(refs)
        try:
            return self._run(self._wait(refs, num_returns, timeout))
        finally:
            if release:
                self._notify_agent_blocked(False)

    async def _wait(self, refs, num_returns, timeout):
        """Event-driven wait (reference: raylet WaitManager — no polling):
        owned refs complete when their memory-store entry lands; borrowed
        refs long-poll the owner's get_object service once."""
        waiters = {self._spawn(self._wait_one(ref)): i
                   for i, ref in enumerate(refs)}
        pending_tasks = set(waiters)
        ready_idx: set = set()
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while pending_tasks and len(ready_idx) < num_returns:
                t = None if deadline is None else max(
                    0.0, deadline - time.monotonic())
                done, pending_tasks = await asyncio.wait(
                    pending_tasks, timeout=t,
                    return_when=asyncio.FIRST_COMPLETED)
                for d in done:
                    if not d.cancelled() and d.exception() is None and \
                            d.result():
                        ready_idx.add(waiters[d])
                if deadline is not None and time.monotonic() >= deadline:
                    break
        finally:
            for p in pending_tasks:
                p.cancel()
        ready = [r for i, r in enumerate(refs) if i in ready_idx]
        pending = [r for i, r in enumerate(refs) if i not in ready_idx]
        return ready, pending

    async def _wait_one(self, ref: ObjectRef) -> bool:
        oid = ref.binary()
        if self.memory_store.contains(oid) or self.store.contains(oid):
            return True
        owner = ref.owner_address or self.address
        if tuple(owner) == self.address:
            await self.memory_store.wait_for(oid, None)
            return True
        # Chunked long-poll (30s slices): bounds owner-side waiter lifetime
        # when this waiter is abandoned, and a transient owner outage is
        # retried instead of resolving the ref as never-ready.
        while True:
            try:
                conn = await self._peer_owner(owner)
                res = await conn.call("get_object",
                                      {"object_id": oid, "timeout_ms": 30_000},
                                      timeout=35)
                if res is not None:
                    return True
            except (rpc.RpcError, asyncio.TimeoutError):
                await asyncio.sleep(0.5)

    # ------------------------------------------------------- normal tasks ----
    def package_runtime_env_cached(self, runtime_env):
        """Driver-side packaging (working_dir/py_modules -> uploaded
        URIs), memoized by content so repeated submissions don't re-zip."""
        if not runtime_env:
            return runtime_env
        import json as _json
        from .runtime_env import package_runtime_env
        key = _json.dumps(runtime_env, sort_keys=True, default=str)
        cached = self._packaged_envs.get(key)
        if cached is None:
            if self._on_loop_thread() and (
                    runtime_env.get("working_dir")
                    or runtime_env.get("py_modules")):
                raise RuntimeError(
                    "working_dir/py_modules packaging uploads to the GCS "
                    "and cannot run on the event loop; package this "
                    "runtime_env from a sync context first")
            cached = self._packaged_envs[key] = package_runtime_env(
                self, runtime_env)
        return cached

    @staticmethod
    def _parse_streaming(num_returns, generator_backpressure):
        """num_returns="streaming" (alias "dynamic") -> (1, streaming-spec)
        (reference: remote_function.py:404 num_returns handling)."""
        if not isinstance(num_returns, str):
            return num_returns, None
        if num_returns not in ("streaming", "dynamic"):
            raise ValueError(
                f"num_returns must be an int, 'streaming' or 'dynamic', "
                f"got {num_returns!r}")
        return 1, {"bp": int(generator_backpressure or 0)}

    def submit_task(self, *, fn, fn_id: Optional[bytes], args, kwargs,
                    num_returns, resources: Dict[str, float],
                    max_retries: int, scheduling_strategy=None,
                    runtime_env=None, name="",
                    fn_blob: Optional[bytes] = None,
                    generator_backpressure: int = 0,
                    sched_key: Optional[bytes] = None,
                    spec_prefix: Optional[tuple] = None,
                    timeout_s: Optional[float] = None) -> List[ObjectRef]:
        """Submit a normal task. NEVER blocks on dependencies: refs are
        minted and returned immediately; pending ObjectRef args resolve on
        the io loop and the task joins the lease queue when they're ready
        (reference: normal_task_submitter.cc + dependency_resolver.cc —
        submission is asynchronous end to end). Sync-safe from any thread,
        including the event loop.

        spec_prefix: optional (prefix_dict, prefix_blob) computed once by
        the RemoteFunction submit cache — per-call spec construction then
        copies the template instead of rebuilding all stable fields, and
        the blob rides every submit_batch frame un-re-encoded."""
        num_returns, streaming = self._parse_streaming(
            num_returns, generator_backpressure)
        # End-to-end deadline: an explicit timeout_s starts the clock
        # here; otherwise a submit made INSIDE a deadline-carrying task
        # inherits that task's remaining budget (the composition rule —
        # nested work never outlives its parent's promise).
        # `is not None`, not truthiness: timeout_s=0 is an already-
        # exhausted budget (e.g. max(0, remaining)) and must expire
        # typed immediately, not silently run unbounded.
        deadline = (time.time() + timeout_s) if timeout_s is not None \
            else deadlines.get()
        if sched_key is None:
            # Caller didn't pre-package: do it here (memoized; raises on
            # the loop thread only for not-yet-cached working_dir uploads).
            runtime_env = self.package_runtime_env_cached(runtime_env)
        refs = self._try_submit_fast(
            fn_id=fn_id, args=args, kwargs=kwargs, num_returns=num_returns,
            resources=resources, max_retries=max_retries,
            scheduling_strategy=scheduling_strategy,
            runtime_env=runtime_env, name=name, streaming=streaming,
            sched_key=sched_key, spec_prefix=spec_prefix,
            deadline=deadline)
        if refs is not None:
            return refs
        return self._submit_task_deferred(
            fn=fn, fn_id=fn_id, args=args, kwargs=kwargs,
            num_returns=num_returns, resources=resources,
            max_retries=max_retries, scheduling_strategy=scheduling_strategy,
            runtime_env=runtime_env, name=name, fn_blob=fn_blob,
            streaming=streaming, sched_key=sched_key,
            spec_prefix=spec_prefix, deadline=deadline)

    def _try_submit_fast(self, *, fn_id, args, kwargs, num_returns,
                         resources, max_retries, scheduling_strategy,
                         runtime_env, name, streaming=None,
                         sched_key=None, spec_prefix=None,
                         deadline=None) -> Optional[List[ObjectRef]]:
        """Submission hot path (reference: the Cython submit_task releases
        the GIL and never blocks on the raylet, _raylet.pyx:3432).  When
        the function is already exported and every arg inlines, the spec
        is built entirely on the calling thread and handed to the io loop
        with call_soon_threadsafe — no cross-thread round trip, so
        .remote() costs ~50us instead of ~0.5ms."""
        if fn_id is None or fn_id not in self._fn_cache:
            return None
        ctx = get_context()
        entries = []
        items = [("", a) for a in args] + list(kwargs.items())
        for kw, a in items:
            if isinstance(a, ObjectRef):
                return None          # dependency resolution needs the loop
            # Best-effort size probe before pickling: buffers/arrays/
            # strings that can't inline would otherwise be serialized
            # here AND again by _build_arg_entries_sync on the slow path.
            # (Large
            # containers without a cheap size still pay double pickling.)
            approx = (len(a) if isinstance(a, (bytes, bytearray, str))
                      else getattr(a, "nbytes", 0))
            if not isinstance(approx, (int, float)):
                # Objects with dynamic __getattr__ (e.g. an ActorHandle
                # answers ANY attribute with an ActorMethod) return
                # non-numeric "nbytes" — treat as size-unknown.
                approx = 0
            if approx > self._inline_limit:
                return None
            ctx.capture = captured = []
            try:
                parts = ctx.serialize(a)
            finally:
                ctx.capture = None
            if captured:
                return None          # nested refs need slow-path pinning
            if ctx.total_size(parts) > self._inline_limit:
                return None          # plasma put needs the loop
            entry = {"v": protocol.concat_parts(parts)}
            if kw:
                entry["kw"] = kw
            entries.append(entry)
        task_id = TaskID.for_normal_task(JobID(self.job_id)).binary()
        if spec_prefix is not None:
            # Pre-encoded submit cache hit: the stable fields were built
            # (and msgpack-encoded) once by the RemoteFunction — per call
            # only the delta fields are written.
            spec = dict(spec_prefix[0])
            spec["task_id"] = task_id
            spec["args"] = entries
            spec["retries_left"] = max_retries
            if streaming is not None:
                spec["streaming"] = streaming
            if deadline is not None:
                spec["deadline"] = deadline
            tr = protocol._trace_inject()
            if tr is not None:
                spec["trace"] = tr
        else:
            spec = protocol.make_task_spec(
                task_id=task_id, job_id=self.job_id, fn_id=fn_id,
                args=entries, nreturns=num_returns,
                owner_addr=list(self.address), resources=resources,
                retries_left=max_retries,
                scheduling_strategy=scheduling_strategy,
                runtime_env=runtime_env, name=name, streaming=streaming,
                deadline=deadline)
        refs = []
        for i in range(num_returns):
            oid = task_id + (i + 1).to_bytes(4, "little")
            self.reference_counter.add_owned(oid, lineage=spec)
            refs.append(ObjectRef(oid, self.address, worker=self))
        if streaming is not None:
            self.register_stream(task_id, streaming["bp"],
                                 expected_attempt=max_retries)
            refs = [ObjectRefGenerator(self, task_id, refs[0])]
        key = sched_key if sched_key is not None else \
            protocol.scheduling_key(fn_id, resources, scheduling_strategy,
                                    runtime_env)

        self.record_task_event(task_id, spec["name"], "SUBMITTED")

        def _enqueue():
            state = self._keys.get(key)
            if state is None:
                state = self._keys[key] = _KeyState(resources,
                                                    scheduling_strategy,
                                                    runtime_env)
                if spec_prefix is not None:
                    state.prefix, state.prefix_blob = spec_prefix
            state.queue.append(_PendingTask(spec, []))
            self._arm_task_deadline(spec)
            # Deferred pump: a burst of submissions landing in this loop
            # tick pumps ONCE, so tasks group into per-lease submit_batch
            # frames instead of one frame each.
            self._schedule_pump(key, state)

        self._post_to_loop(_enqueue)
        return refs

    def _deferred_pump(self, key: bytes, state):
        state.pump_scheduled = False
        self._pump(key, state)

    def _note_task_latency(self, state: _KeyState, dt: float) -> None:
        state.avg_task_s = dt if state.avg_task_s is None \
            else 0.8 * state.avg_task_s + 0.2 * dt
        # Adaptive window (reference: normal_task_submitter.cc
        # max_tasks_in_flight_per_worker): deepen while the pipeline keeps
        # push->complete latency low, back off once tasks are slow enough
        # that queuing them here (where lease growth / spillback can still
        # spread them) beats parking them behind one worker.
        if state.avg_task_s < 0.05:
            if state.window < self._max_inflight:
                state.window = min(self._max_inflight, state.window * 2)
        elif state.avg_task_s > 0.25 and state.window > PIPELINE_DEPTH:
            state.window = max(PIPELINE_DEPTH, state.window // 2)

    def _schedule_pump(self, key: bytes, state):
        """Pump at the END of the current loop tick: a burst of replies
        landing together then dispatches the next wave as per-lease
        multi-call frames instead of one single-task frame per reply."""
        if not state.pump_scheduled:
            state.pump_scheduled = True
            self.loop.call_soon(self._deferred_pump, key, state)

    def _submit_task_deferred(self, *, fn, fn_id, args, kwargs, num_returns,
                              resources, max_retries, scheduling_strategy,
                              runtime_env, name, fn_blob, streaming,
                              sched_key, spec_prefix=None,
                              deadline=None) -> List[ObjectRef]:
        """Slow-path submission (ref args / oversized args / unexported
        fn) without blocking the caller: args serialize on the CALLING
        thread (post-call mutation is safe, matching the fast path and
        actor submission), refs return immediately, and a loop coroutine
        exports the function, stores oversized args, awaits pending deps,
        then enqueues + pumps (reference: dependency_resolver.cc — the
        task enters the lease queue only once its deps exist)."""
        ctx = get_context()
        if fn_id is None or fn_id not in self._fn_cache:
            if fn_blob is None:
                fn_blob = ctx.dumps_code(fn)
                fn_id = protocol.function_id(fn_blob)
        export = (fn, fn_id, fn_blob) if fn_id not in self._fn_cache \
            else None
        entries, ref_args, borrowed_args, big_puts = \
            self._build_arg_entries_sync(args, kwargs)
        task_id = TaskID.for_normal_task(JobID(self.job_id)).binary()
        spec = protocol.make_task_spec(
            task_id=task_id, job_id=self.job_id, fn_id=fn_id,
            args=entries, nreturns=num_returns,
            owner_addr=list(self.address),
            resources=resources, retries_left=max_retries,
            scheduling_strategy=scheduling_strategy, runtime_env=runtime_env,
            name=name or getattr(fn, "__name__", ""), streaming=streaming,
            deadline=deadline)
        refs = []
        for i in range(num_returns):
            oid = task_id + (i + 1).to_bytes(4, "little")
            self.reference_counter.add_owned(oid, lineage=spec)
            refs.append(ObjectRef(oid, self.address, worker=self))
        if streaming is not None:
            self.register_stream(task_id, streaming["bp"],
                                 expected_attempt=max_retries)
            refs = [ObjectRefGenerator(self, task_id, refs[0])]
        key = sched_key if sched_key is not None else \
            protocol.scheduling_key(fn_id, resources, scheduling_strategy,
                                    runtime_env)
        task = _PendingTask(spec, ref_args, borrowed_args)
        self.record_task_event(task_id, spec["name"], "SUBMITTED")

        async def _finish():
            try:
                if export is not None:
                    try:
                        await self._export_function(
                            export[0], fn_id=export[1], blob=export[2])
                    except Exception as e:
                        self._store_task_exception(spec, exc.RayError(
                            f"function export failed: {e}"))
                        self._release_task_pins(task)
                        return
                if big_puts or any("ref" in e for e in spec["args"]):
                    if not await self._resolve_task_args(spec, task,
                                                         big_puts):
                        return
            except asyncio.CancelledError:
                # ray_tpu.cancel() while deps were resolving (_cancel
                # cancels this coroutine): resolve the returns NOW instead
                # of whenever the dep lands.
                self._store_task_exception(spec, exc.TaskCancelledError(
                    f"{spec['name']} cancelled"))
                self._release_task_pins(task)
                return
            finally:
                self._resolving.pop(task_id, None)
            if task_id in self._cancelled:
                # Cancelled while deps were resolving: never enqueue.
                self._cancelled.discard(task_id)
                self._store_task_exception(spec, exc.TaskCancelledError(
                    f"{spec['name']} cancelled"))
                self._release_task_pins(task)
                return
            state = self._keys.get(key)
            if state is None:
                state = self._keys[key] = _KeyState(
                    resources, scheduling_strategy, runtime_env)
                if spec_prefix is not None:
                    state.prefix, state.prefix_blob = spec_prefix
            state.queue.append(task)
            self._schedule_pump(key, state)

        def _start():
            self._arm_task_deadline(spec)
            # Eager task execution can run _finish to completion INSIDE
            # this _spawn call (everything already resolved, no suspension
            # point) — its finally-pop would then precede this assignment
            # and a stale done-task entry would shadow the real pushed
            # task from _cancel forever. Register only live coroutines.
            t = self._spawn(_finish())
            if not t.done():
                self._resolving[task_id] = t

        if self._on_loop_thread():
            _start()
        else:
            self._post_to_loop(_start)
        return refs

    async def _export_function(self, fn, fn_id=None, blob=None) -> bytes:
        if blob is None:
            ctx = get_context()
            blob = ctx.dumps_code(fn)
            fn_id = protocol.function_id(blob)
        if fn_id not in self._fn_cache:
            await self.gcs.call("kv_put", {
                "ns": "fn", "key": fn_id.hex(), "value": blob,
                "overwrite": False})
            self._fn_cache[fn_id] = fn
        return fn_id

    def _pump(self, key: bytes, state: _KeyState):
        """Dispatch queued tasks onto leased workers; grow leases on demand
        (reference: normal_task_submitter.cc lease pool + pipelining)."""
        # Breadth-first: one task per lease per wave, so a burst of long
        # tasks spreads across all workers before any lease pipelines a
        # second push.  While more leases are still in flight, hold at
        # depth 1 — pipelining is only for hiding RTT once the cluster
        # has granted all the concurrency it's going to.  When observed
        # task latency is SHORT (EMA < 50ms), deepen to the adaptive
        # window (grown by _note_task_latency toward
        # max_tasks_in_flight_per_worker) so each worker receives a chunk
        # worth amortizing (one submit_batch frame, one executor hop per
        # chunk) instead of trickling a few tasks per completion round
        # trip — binding a burst of sub-50ms tasks to the granted leases
        # costs at most a few hundred ms even if the pool later grows.
        # Long/unknown tasks never deep-pipeline: they must stay queued
        # here so lease growth (and spillback to other nodes) can still
        # spread them.
        if state.avg_task_s is not None and state.avg_task_s < 0.05:
            # Short tasks deepen even while lease requests are parked at a
            # saturated agent: a parked request may not resolve for
            # seconds.
            depth_cap = max(PIPELINE_DEPTH,
                            min(state.window, len(state.queue)
                                // max(1, len(state.leases))))
        elif state.pending_lease_requests > 0:
            depth_cap = 1
        else:
            depth_cap = PIPELINE_DEPTH
        assign: Dict[int, tuple] = {}
        for depth in range(depth_cap):
            if not state.queue:
                break
            for lease in state.leases:
                if not state.queue:
                    break
                if lease.conn.closed or lease.inflight > depth:
                    continue
                task = state.queue.popleft()
                lease.inflight += 1
                assign.setdefault(id(lease), (lease, []))[1].append(task)
        for lease, tasks in assign.values():
            # One submit_batch frame per lease per pump wave: identical
            # per-task semantics to separate pushes (the worker executes
            # them serially off its task lock either way), amortized
            # framing, and completions return as coalesced complete_batch
            # frames.
            self._spawn(self._push_batch(key, state, lease, tasks))
        if time.monotonic() < state.lease_backoff_until:
            return          # saturated: the denied-retry loop re-pumps
        max_leases = get_config().max_leases_per_scheduling_key
        want = min(len(state.queue), max_leases - len(state.leases)
                   - state.pending_lease_requests)
        for _ in range(max(0, want)):
            state.pending_lease_requests += 1
            self._spawn(self._request_lease(key, state))

    def _report_demand(self, key: bytes, state: _KeyState):
        """Tell the GCS this scheduling key has unschedulable tasks so the
        autoscaler can launch capacity (rate-limited per key; reference:
        backlog size in lease requests feeding autoscaler demand)."""
        now = time.monotonic()
        last = getattr(state, "last_demand_report", 0.0)
        if now - last < 2.0:
            return
        state.last_demand_report = now
        shapes = [{"resources": state.resources,
                   "count": max(1, len(state.queue))}]
        self._spawn(self.gcs.call("report_demand", {
            "reporter": self.worker_id + key,
            "shapes": shapes}))

    async def _request_lease(self, key: bytes, state: _KeyState,
                             agent_conn: Optional[rpc.Connection] = None,
                             hops: int = 0):
        strat = state.strategy or {}
        is_pg = strat.get("type") == "placement_group"
        if agent_conn is None and strat.get("type") in (
                "spread", "node_affinity", "node_label"):
            # Submitter-side raylet choice for non-default strategies
            # (reference: lease_policy.cc picks the target raylet before
            # the lease request leaves the worker).
            routed, verdict = await self._route_lease_agent(
                strat, state.resources)
            if verdict == "retry":
                # Transient (stale view / unreachable-but-listed node):
                # keep the tasks queued and try again — the refreshed
                # GCS view either finds the node or declares it dead.
                state.pending_lease_requests -= 1
                if state.queue:
                    await asyncio.sleep(0.5)
                    self._pump(key, state)
                return
            if verdict == "infeasible":
                state.pending_lease_requests -= 1
                self._fail_queued_tasks(state, exc.RayError(
                    f"scheduling strategy {strat.get('type')} has no "
                    "satisfiable node (hard constraint)"))
                return
            agent_conn = routed
        loc_map = self._lease_locality_map(state) \
            if agent_conn is None and not strat.get("type") else None
        if loc_map:
            # Default-strategy tasks with large by-ref args: route the
            # lease to the node already holding the bytes (reference:
            # lease_policy.cc locality-aware raylet choice driven by the
            # owner's location table).  Locality only ever picks among
            # feasible, targetable, trusted nodes — a miss falls through
            # to the local agent exactly as before.
            routed = await self._locality_lease_agent(state, loc_map)
            if routed is not None:
                agent_conn = routed
        if agent_conn is None and is_pg:
            # Route the lease to the agent hosting the target bundle — the
            # local agent may not hold it at all (reference: lease_policy.cc
            # picks the raylet by bundle locality).
            status, agent_conn = await self._pg_agent_conn(strat)
            if status == "removed":
                state.pending_lease_requests -= 1
                self._fail_queued_tasks(
                    state, exc.RayError(
                        "placement group was removed; task can never be "
                        "scheduled"))
                return
            if agent_conn is None:       # PG still pending / node down
                state.pending_lease_requests -= 1
                if state.queue:
                    await asyncio.sleep(0.2)
                    self._pump(key, state)
                return
        agent_conn = agent_conn or self.agent
        try:
            res = await agent_conn.call("request_lease", {
                "resources": state.resources,
                "runtime_env": state.runtime_env,
                "placement_group": ({"pg_id": strat["pg_id"],
                                     "bundle_index":
                                     strat.get("bundle_index", 0)}
                                    if is_pg else None),
                # Large by-ref args of the queued tasks this lease will
                # serve: the granting agent starts pulling missing ones
                # IMMEDIATELY (fetch overlaps worker dispatch/queueing)
                # and its spillback choice scores bytes-already-local.
                "prefetch": self._lease_prefetch_entries(state),
                # Fencing token: an agent that has seen a NEWER cluster
                # epoch (GCS failover) rejects this typed so we refresh
                # and resubmit instead of acting on a stale grant.
                protocol.EPOCH_KEY: self.cluster_epoch,
            }, timeout=130)
        except (rpc.RpcError, asyncio.TimeoutError):
            state.pending_lease_requests -= 1
            if state.queue:
                await asyncio.sleep(0.2)
                self._pump(key, state)
            return
        if not res.get("granted"):
            if res.get("reject") == protocol.REJECT_STALE_EPOCH:
                # Fenced: this owner's epoch predates a GCS failover the
                # agent already lives in.  Adopt the agent's epoch and
                # resubmit through the normal pump — the queued tasks were
                # never granted, so the retry is exactly-once by
                # construction (reference: Raft clients retry with the
                # new term; StaleEpochError is the user-facing type when
                # a caller surfaces this instead of retrying).
                self.stale_epoch_rejections += 1
                self._learn_epoch(res.get(protocol.EPOCH_KEY))
                state.pending_lease_requests -= 1
                if state.queue:
                    self._pump(key, state)
                return
            reason = res.get("reason") or ""
            if "runtime env setup failed" in reason:
                # A broken env spec (bad package, dead find_links) can
                # never succeed by retrying — surface it on the tasks
                # (reference: RuntimeEnvSetupError fails the task).
                state.pending_lease_requests -= 1
                self._fail_queued_tasks(state, exc.RayError(reason))
                return
            if is_pg and "bundle" in (res.get("reason") or ""):
                # Bundle gone or exhausted at the routed node: drop the
                # cached table so the next attempt re-resolves (and notices
                # PG removal).
                self._pg_cache.pop(strat["pg_id"], None)
            spill = res.get("spillback")
            if strat.get("type") == "node_affinity" and \
                    not strat.get("soft"):
                spill = None   # hard affinity never follows spillback
            if strat.get("type") == "node_label" and strat.get("hard"):
                spill = None   # hard label selector likewise
            if spill and hops < 4:
                try:
                    peer = await self._peer_owner(tuple(spill))
                    await self._request_lease(key, state, peer, hops + 1)
                    return
                except rpc.ConnectionLost:
                    pass
            state.pending_lease_requests -= 1
            if state.queue:
                retry_s = res.get("retry_after_ms", 100) / 1000
                # Report demand on saturation as well as infeasibility: a
                # cluster where the shape *fits* but every node is busy still
                # needs the autoscaler to see the queued backlog (reference
                # scales on lease backlog, not only infeasible shapes).
                self._report_demand(key, state)
                # Stop hot-looping new lease requests while the cluster is
                # saturated; held leases pipeline in the meantime.
                state.lease_backoff_until = time.monotonic() + retry_s
                await asyncio.sleep(retry_s)
                state.lease_backoff_until = 0.0
                self._pump(key, state)
            return
        state.pending_lease_requests -= 1
        if state.last_demand_report:
            # Demand satisfied: retract the report instead of letting it
            # age out over the TTL (stale shapes over-provision).
            state.last_demand_report = 0.0
            self._spawn(self.gcs.call("report_demand", {
                "reporter": self.worker_id + key, "shapes": []}))
        if not state.queue and not any(ls.inflight for ls in state.leases):
            # Stale grant: the work this request was made for already
            # drained (typical when several requests parked at a saturated
            # agent). Hand the lease straight back — cycling it through
            # the idle reaper would hold the slot ~0.75s, serializing
            # OTHER clients' parked requests behind it (reference:
            # normal_task_submitter.cc cancels unneeded lease requests).
            self._spawn(agent_conn.call(
                "return_lease", {"lease_id": res["lease_id"]}))
            return
        worker_addr = tuple(res["worker_addr"])
        grant_epoch = res.get(protocol.EPOCH_KEY)
        if isinstance(grant_epoch, int):
            self._learn_epoch(grant_epoch)
        conn = await self._worker_conn(worker_addr)
        lease = _Lease(res["lease_id"], worker_addr, res["worker_id"], conn,
                       agent_conn,
                       epoch=(grant_epoch if isinstance(grant_epoch, int)
                              else self.cluster_epoch))
        state.leases.append(lease)
        self._pump(key, state)
        self._spawn(self._lease_reaper(key, state, lease))

    def _learn_epoch(self, epoch):
        """Adopt a higher cluster epoch (GCS failover observed).  Cached
        idle leases minted under the old epoch are handed back — their
        grants are formally fenced, and the replacement request returns
        a fresh same-worker lease stamped with the new epoch.  Leases
        with work in flight finish it first (the executing worker and
        its agent are both still alive; only the grant token aged)."""
        if not isinstance(epoch, int) or epoch <= self.cluster_epoch:
            return
        prev = self.cluster_epoch
        self.cluster_epoch = epoch
        if prev == protocol.EPOCH_NONE:
            return
        logger.warning("cluster epoch bumped %d -> %d (GCS failover)",
                       prev, epoch)
        for key, state in self._keys.items():
            stale = [ls for ls in state.leases
                     if ls.epoch < epoch and not ls.inflight]
            for ls in stale:
                state.leases.remove(ls)
                self._spawn(ls.agent_conn.call(
                    "return_lease", {"lease_id": ls.lease_id,
                                     protocol.EPOCH_KEY: epoch}))
            if stale and state.queue:
                self._pump(key, state)

    async def _cluster_nodes(self, force: bool = False):
        """GCS node view, cached briefly (strategy routing must not add
        a GCS round trip per lease request).  Refreshes are DELTA
        queries (`get_nodes {"since": epoch}`): the GCS ships only the
        views whose scheduling-relevant state changed since our last
        poll, so N polling clients cost the GCS O(changes) per tick
        instead of O(nodes) full-view builds each.  A plain-list reply
        (pre-delta GCS) keeps working unchanged."""
        now = time.monotonic()
        cached = getattr(self, "_nodes_cache", None)
        if not force and cached is not None and now - cached[0] < 2.0:
            return cached[1]
        by_id = getattr(self, "_nodes_by_id", None)
        since = getattr(self, "_nodes_epoch", None)
        res = await self.gcs.call(
            "get_nodes",
            {"since": since if by_id and since is not None else -1})
        if isinstance(res, list):
            by_id = {n["node_id"]: n for n in res}
            self._nodes_epoch = None
        else:
            if by_id is None or since is None:
                by_id = {}
            for v in res["changed"]:
                by_id[v["node_id"]] = v
            if res.get("total") is not None and res["total"] != len(by_id):
                # Node-table reset under us (GCS restarted without its
                # journal): ghosts in our merge would never be sent as
                # dead — bootstrap the view from scratch.
                res = await self.gcs.call("get_nodes", {"since": -1})
                by_id = {v["node_id"]: v for v in res["changed"]}
            self._nodes_epoch = res["epoch"]
        self._nodes_by_id = by_id
        nodes = list(by_id.values())
        self._nodes_cache = (now, nodes)
        return nodes

    def _lease_locality_map(self, state) -> Optional[dict]:
        """Bytes-already-local map for the task at the head of this
        scheduling key's queue (the one the requested lease will run
        first), or None when locality scheduling is disabled / has
        nothing to say."""
        cfg = get_config()
        if not (cfg.object_locality_scheduling_enabled
                and cfg.replica_directory_enabled and state.queue):
            return None
        from . import scheduling_policy as policy
        loc = policy.arg_locality(state.queue[0].spec.get("args"))
        if not loc or max(loc.values()) < cfg.object_locality_min_bytes:
            return None
        return loc

    async def _locality_lease_agent(self, state, loc_map):
        """Agent connection for the targetable+trusted+feasible node
        holding the most hinted arg bytes; None keeps the local agent.
        Feasibility, draining state and gray-suspicion all rank ABOVE
        locality — a byte-holding node that fails any of them is simply
        not a candidate."""
        from . import scheduling_policy as policy
        try:
            nodes = [n for n in await self._cluster_nodes()
                     if policy.targetable(n)]
        except (rpc.RpcError, asyncio.TimeoutError):
            return None
        cands = [(tuple(n["address"]), tuple(n["address"]),
                  n["resources_total"], n["resources_available"])
                 for n in policy.prefer_trusted(nodes)]
        best = policy.pick_by_locality(
            cands, state.resources, loc_map,
            min_bytes=get_config().object_locality_min_bytes)
        if best is None or best == self.agent_address:
            return None
        try:
            return await self._peer_owner(best)
        except (rpc.ConnectionLost, rpc.RpcError, OSError):
            return None     # stale view: the local agent still works

    def _lease_prefetch_entries(self, state, limit: int = 8):
        """[oid, locations, owner_addr, size, task_id] for the large
        by-ref args of the first few queued tasks — the granting agent's
        prefetch work list (missing ones start pulling on grant)."""
        cfg = get_config()
        if not (cfg.arg_prefetch_enabled and cfg.replica_directory_enabled):
            return None
        out, seen = [], set()
        for task in list(state.queue)[:4]:
            for e in task.spec.get("args") or ():
                if "ref" not in e or \
                        int(e.get("sz") or 0) < cfg.arg_prefetch_min_bytes:
                    continue
                oid = bytes(e["ref"][0])
                locs = e["ref"][2]
                if oid in seen or not locs:
                    continue
                seen.add(oid)
                out.append([oid, locs, list(e["ref"][1]),
                            int(e["sz"]), task.spec["task_id"]])
                if len(out) >= limit:
                    return out
        return out or None

    async def _route_lease_agent(self, strat: dict, resources):
        """Pick the agent to lease from for spread / node_affinity /
        node_label tasks (reference: lease_policy.cc +
        scheduling/policy/{spread,node_affinity,node_label}*).

        Returns (conn, verdict): verdict 'ok' with a connection,
        'retry' for transient state (stale node view, GCS hiccup, a
        listed-alive node refusing connections — death-lag), or
        'infeasible' when a HARD constraint is unsatisfiable per the
        authoritative GCS view (target dead/absent, no label match)."""
        hard = ((strat.get("type") == "node_affinity"
                 and not strat.get("soft"))
                or (strat.get("type") == "node_label"
                    and strat.get("hard")))
        from . import scheduling_policy as policy
        try:
            all_nodes = [n for n in await self._cluster_nodes()
                         if policy.targetable(n)]
            # Gray-failure deprioritization (ranking only: node_affinity
            # resolves against the UNFILTERED view below — an explicitly
            # targeted suspect node is deprioritized elsewhere, never
            # hidden from its own affinity match).
            nodes = all_nodes if hard else policy.prefer_trusted(all_nodes)
        except (rpc.RpcError, asyncio.TimeoutError):
            # Never silently violate a hard constraint on a GCS blip.
            return (None, "retry") if hard else (self.agent, "ok")
        conn, verdict = await self._route_on_view(strat, resources, nodes,
                                                  hard, all_nodes)
        if verdict == "infeasible":
            # The cached view can be up to 2s stale — a node that just
            # registered must not get its hard-pinned tasks wrongly
            # failed.  Re-evaluate against a FRESH view before declaring
            # the constraint unsatisfiable.
            try:
                all_nodes = [n for n in
                             await self._cluster_nodes(force=True)
                             if policy.targetable(n)]
            except (rpc.RpcError, asyncio.TimeoutError):
                return None, "retry"
            conn, verdict = await self._route_on_view(
                strat, resources, all_nodes, hard, all_nodes)
        return conn, verdict

    async def _route_on_view(self, strat: dict, resources, nodes, hard,
                             all_nodes=None):
        from . import scheduling_policy as policy
        typ = strat.get("type")

        async def _connect(addr):
            try:
                return await self._peer_owner(tuple(addr))
            except (rpc.ConnectionLost, rpc.RpcError, OSError):
                # Listed alive but unreachable: either restarting or the
                # health check hasn't marked it dead yet — let the caller
                # retry; the refreshed view converges either way.
                self._nodes_cache = None
                return None

        if typ == "node_affinity":
            target = bytes(strat["node_id"])
            # Resolve against the unfiltered targetable view: a soft
            # affinity to a gray-suspect node is an explicit locality
            # preference, not a placement the scheduler chose — hiding
            # it behind prefer_trusted would hard-exclude the target.
            node = next((n for n in (all_nodes or nodes)
                         if bytes(n["node_id"]) == target), None)
            if node is None:
                # Authoritative: the target is dead/absent in the view.
                return (self.agent, "ok") if strat.get("soft") \
                    else (None, "infeasible")
            conn = await _connect(node["address"])
            if conn is not None:
                return conn, "ok"
            return (self.agent, "ok") if strat.get("soft") \
                else (None, "retry")
        if typ == "node_label":
            # Like node_affinity above: label MATCHING sees the
            # unfiltered view — suspicion deprioritizes (ranks suspects
            # last, below), never hard-excludes, so a label whose only
            # match is gray-suspect still resolves instead of silently
            # dropping the preference.
            pool = all_nodes if all_nodes is not None else nodes
            ordered = policy.label_filter(
                [(tuple(n["address"]), n.get("labels") or {})
                 for n in pool],
                strat.get("hard") or None, strat.get("soft") or None)
            if not ordered:
                return (None, "infeasible") if hard else (self.agent, "ok")
            by_addr = {tuple(n["address"]): n for n in pool}
            # Feasible matches first, trusted before suspect within,
            # then any match (its agent backpressures; spillback is
            # suppressed for hard).
            for addr in sorted(ordered, key=lambda a: (
                    not policy.feasible(
                        by_addr[a]["resources_available"], resources),
                    policy.suspicion_of(by_addr[a])
                    >= policy.SUSPECT_THRESHOLD)):
                conn = await _connect(addr)
                if conn is not None:
                    return conn, "ok"
            return (None, "retry") if hard else (self.agent, "ok")
        if typ == "spread":
            feas = [n for n in nodes
                    if policy.feasible(n["resources_available"],
                                       resources)] or nodes
            self._spread_rr = getattr(self, "_spread_rr", -1) + 1
            for i in range(len(feas)):
                node = feas[(self._spread_rr + i) % len(feas)]
                conn = await _connect(node["address"])
                if conn is not None:
                    return conn, "ok"
            return self.agent, "ok"
        return self.agent, "ok"

    async def _pg_agent_conn(self, strat: dict):
        """Resolve the agent hosting a PG-targeted lease's bundle.

        Returns (status, conn): ("ok", conn) | ("pending", None) |
        ("removed", None).  Bundle locations are immutable once placed, so
        the table is cached until a denial invalidates it; bundle_index -1
        round-robins across the PG's nodes."""
        pg_id = strat["pg_id"]
        table = self._pg_cache.get(pg_id)
        if table is None:
            table = await self.gcs.call("get_placement_group",
                                        {"pg_id": pg_id})
            if table is None or table.get("state") == "REMOVED":
                return "removed", None
            if table.get("state") != "CREATED":
                return "pending", None
            self._pg_cache[pg_id] = table
        bundles = table["bundles"]
        idx = strat.get("bundle_index", 0)
        if idx >= len(bundles):
            return "removed", None     # invalid index: task can never run
        if idx < 0:
            n = self._pg_rr.get(pg_id, 0)
            self._pg_rr[pg_id] = n + 1
            idx = n % len(bundles)
        addr = tuple(bundles[idx]["node_addr"])
        if addr == self.agent_address:
            return "ok", self.agent
        try:
            return "ok", await self._peer_owner(addr)
        except rpc.ConnectionLost:
            return "pending", None

    def _fail_queued_tasks(self, state: _KeyState, error: Exception):
        """Resolve every queued task's return refs to an error."""
        while state.queue:
            task = state.queue.popleft()
            self._store_task_exception(task.spec, error)
            self._release_task_pins(task)

    async def _worker_conn(self, addr: tuple) -> rpc.Connection:
        conn = self._worker_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(addr, name="cw->worker", retries=3,
                                     on_close=self._on_peer_conn_close)
            # Batched completions ride back on this same connection.
            conn.fast_handlers["complete_batch"] = self._f_complete_batch
            self._worker_conns[addr] = conn
        return conn

    async def _lease_reaper(self, key, state, lease: _Lease):
        # 100ms grace keeps the lease across back-to-back sync submission
        # loops (gap ~0) but hands the worker back quickly when this key's
        # queue drains — under saturation other clients' lease requests
        # are parked at the agent behind this slot (reference:
        # normal_task_submitter.cc returns the worker when the scheduling
        # key's queue empties; the raylet's idle pool, not a held lease,
        # provides reuse).
        while True:
            await asyncio.sleep(0.05)
            if lease not in state.leases:
                # Already handed back elsewhere (e.g. fenced as stale on
                # an epoch bump) — nothing left to reap.
                return
            if lease.conn.closed:
                state.leases.remove(lease)
                return
            if lease.inflight == 0 and not state.queue:
                if time.monotonic() - lease.idle_since > 0.1:
                    if lease in state.leases:
                        state.leases.remove(lease)
                    try:
                        await lease.agent_conn.call(
                            "return_lease", {"lease_id": lease.lease_id})
                    except rpc.RpcError:
                        pass
                    return

    async def _push_batch(self, key, state, lease: _Lease, tasks):
        """Push queued tasks to one leased worker as a single submit_batch
        frame: one pre-encoded spec prefix + per-task deltas (see
        docs/control_plane.md).  The worker acks enqueue immediately and
        ships results back as coalesced complete_batch frames, applied by
        _f_complete_batch.  Per-task semantics (cancel checks,
        retry/requeue on worker death, OOM triage) match the per-call
        pushes this replaces; the worker executes the batch serially off
        its task lock exactly as it would pipelined singles.

        A lost ack (chaos drop / wedged worker) resends the
        still-unfinished tasks after submit_batch_ack_timeout_s — the
        worker dedups by task id, so a dropped RESPONSE is harmless and a
        dropped REQUEST simply re-enqueues."""
        ready = []
        for task in tasks:
            spec = task.spec
            tid = spec["task_id"]
            if tid in self._cancelled:
                lease.inflight -= 1
                self._store_task_exception(
                    spec, exc.TaskCancelledError(f"{spec['name']} cancelled"))
                self._release_task_pins(task)
                self._cancelled.discard(tid)
                continue
            ready.append(task)
        if not ready:
            self._pump(key, state)
            return
        if lease.conn.closed:
            await self._lease_lost(key, state, lease, ready)
            return
        # Note: a transport write-buffer pause is NOT treated as a shrink
        # signal — the pause already throttles emission, and on a
        # saturated host it fires constantly; halving on it collapses the
        # pipeline exactly when deep batching pays most.  The window
        # shrinks on the real backpressure signals instead: rising
        # push->complete latency (_note_task_latency) and lease loss
        # (_lease_lost).
        if state.prefix is None:
            state.prefix = protocol.spec_prefix_of(ready[0].spec)
            state.prefix_blob = protocol.encode_prefix(state.prefix)
        t_push = time.monotonic()
        for task in ready:
            tid = task.spec["task_id"]
            self._inflight_tasks[tid] = lease
            self._pending_replies[tid] = ("n", key, state, lease, task,
                                          t_push)
        outcome, info = await self._submit_batch_with_ack(
            lease.conn, state.prefix, state.prefix_blob, ready,
            actor=False, abort_label=str(lease.worker_addr))
        if outcome == "remote_error":
            # Dispatch-level failure: fail the tasks, keep the lease
            # accounted.
            e, pending = info
            for task in pending:
                tid = task.spec["task_id"]
                if self._pending_replies.pop(tid, None) is None:
                    continue
                self._inflight_tasks.pop(tid, None)
                lease.inflight -= 1
                self._store_task_exception(task.spec, exc.RayError(
                    f"task push failed: {e}"))
                self._release_task_pins(task)
            self._schedule_pump(key, state)
        elif outcome == "conn_lost":
            # The conn's on_close cleanup usually runs first and sweeps
            # these entries; handle whatever it hasn't claimed.
            leftovers = [t for t in ready
                         if self._pending_replies.pop(t.spec["task_id"],
                                                      None) is not None]
            if leftovers:
                await self._lease_lost(key, state, lease, leftovers)

    async def _submit_batch_with_ack(self, conn, prefix, prefix_blob,
                                     pending, *, actor: bool,
                                     abort_label: str):
        """Send one submit_batch frame and drive the lost-ack resend loop
        (shared by the normal-task and actor arms — the protocol must
        never diverge between them).

        Returns ("ok", _) once acked or nothing is left pending,
        ("remote_error", (err, still_pending)) on a dispatch-level
        RemoteError, ("conn_lost", _) when the connection died mid-call
        (the caller sweeps leftovers).  Tasks whose completions land
        during the retry loop drop out of the resend via the
        _pending_replies membership filter; the receiver dedups re-sent
        ids.  Acks lost 4x in a row mean the worker (or the wire) is
        wedged: recycle the connection — its on_close cleanup funnels the
        in-flight tasks through the normal worker-death semantics."""
        for _attempt in range(4):
            if self._shutdown:
                # Don't resend (or resume bookkeeping) against a runtime
                # that is tearing down.
                return "ok", None
            payload = {"pr": prefix_blob,
                       "t": [protocol.spec_delta(prefix, t.spec)
                             for t in pending]}
            if actor:
                payload["a"] = True
            try:
                await conn.call("submit_batch", payload,
                                timeout=self._ack_timeout)
                return "ok", None   # completions arrive via complete_batch
            except asyncio.TimeoutError:
                pending = [t for t in pending
                           if t.spec["task_id"] in self._pending_replies]
                if not pending:
                    return "ok", None
            except rpc.RemoteError as e:
                return "remote_error", (e, pending)
            except rpc.ConnectionLost:
                return "conn_lost", None
        logger.warning("submit_batch acks lost to %s; recycling "
                       "connection", abort_label)
        conn.abort()
        return "ok", None

    def _f_complete_batch(self, conn, p):
        """Fast handler on direct worker/actor connections: a peer shipped
        a coalesced batch of push results.  Applies every reply's refcount
        + memory-store updates in one pass and schedules a single deferred
        pump per affected scheduling key (the group wakeup) — no per-reply
        RPC future, callback, or asyncio Task."""
        now = time.monotonic()
        for tid, reply in p["t"]:
            tid = bytes(tid)
            rec = self._pending_replies.pop(tid, None)
            if rec is None:
                continue    # already resolved by connection-loss cleanup
            if rec[0] == "n":
                _, key, state, lease, task, t_push = rec
                self._inflight_tasks.pop(tid, None)
                lease.inflight -= 1
                lease.idle_since = now
                self._note_task_latency(state, now - t_push)
                try:
                    self._handle_reply(task.spec, task, reply)
                except Exception:
                    logger.exception("completion handling failed for %s",
                                     task.spec.get("name"))
                # Pump even when reply handling blew up: inflight was
                # already decremented, and a skipped pump would leave the
                # key's queue idle until some unrelated event wakes it.
                self._schedule_pump(key, state)
            else:
                _, astate, _conn, task, _t_push = rec
                self._inflight_actor_tasks.pop(tid, None)
                try:
                    self._handle_reply(task.spec, task, reply)
                except Exception:
                    logger.exception("completion handling failed for %s",
                                     task.spec.get("method"))
        return True

    def _on_peer_conn_close(self, conn):
        """A direct worker/actor connection died: every task whose
        completion was pending on it gets the per-task retry/cancel/fail
        treatment (same semantics as a lost per-call push reply)."""
        if self._shutdown or not self._pending_replies:
            return
        self._spawn(self._conn_lost_cleanup(conn))

    async def _conn_lost_cleanup(self, conn):
        by_lease: Dict[int, tuple] = {}
        by_actor: Dict[int, tuple] = {}
        for tid, rec in list(self._pending_replies.items()):
            if rec[0] == "n":
                _, key, state, lease, task, _t = rec
                if lease.conn is conn:
                    self._pending_replies.pop(tid, None)
                    by_lease.setdefault(
                        id(lease), (key, state, lease, []))[3].append(task)
            else:
                _, astate, pushed_conn, task, _t = rec
                if pushed_conn is conn:
                    self._pending_replies.pop(tid, None)
                    by_actor.setdefault(
                        id(astate), (astate, []))[1].append(task)
        for key, state, lease, tasks in by_lease.values():
            await self._lease_lost(key, state, lease, tasks)
        for astate, tasks in by_actor.values():
            # Only clear the actor's conn if it still points at the DEAD
            # connection — a concurrent retry may already have
            # reconnected, and clobbering the healthy conn would split
            # subsequent calls across two connections (breaking the
            # sequential actor's arrival ordering).
            if astate.conn is conn:
                astate.conn = None
            await self._actor_tasks_lost(astate, tasks)

    async def _lease_lost(self, key, state, lease: _Lease, tasks):
        """The leased worker's connection died with these tasks in flight:
        requeue retryable ones, fail the rest (with one OOM triage against
        the agent for the whole burst)."""
        if lease in state.leases:
            state.leases.remove(lease)
        if state.window > PIPELINE_DEPTH:
            # A died worker is the strongest backpressure signal there is.
            state.window = max(PIPELINE_DEPTH, state.window // 2)
        fate = None
        need_fate = any(
            t.spec["retries_left"] == 0
            and t.spec["task_id"] not in self._cancelled for t in tasks)
        if need_fate:
            try:
                fate = await lease.agent_conn.call(
                    "worker_fate", {"worker_id": lease.worker_id}, timeout=5)
            except (rpc.RpcError, asyncio.TimeoutError):
                pass
        for task in tasks:
            spec = task.spec
            tid = spec["task_id"]
            self._inflight_tasks.pop(tid, None)
            lease.inflight -= 1
            if tid in self._cancelled:
                self._store_task_exception(
                    spec, exc.TaskCancelledError(f"{spec['name']} cancelled"))
                self._release_task_pins(task)
                self._cancelled.discard(tid)
            elif spec["retries_left"] != 0:
                # Negative = retry forever (max_retries=-1, reference
                # semantics); only positive budgets are consumed.
                if spec["retries_left"] > 0:
                    spec["retries_left"] -= 1
                self._stream_reset_for_retry(spec)
                state.queue.append(task)
            else:
                if fate and fate.get("oom_killed"):
                    err = exc.OutOfMemoryError(fate.get("reason") or (
                        f"worker at {lease.worker_addr} was OOM-killed "
                        f"running {spec['name']}"))
                else:
                    err = exc.WorkerCrashedError(
                        f"worker at {lease.worker_addr} died running "
                        f"{spec['name']}")
                self._store_task_failure(spec, err)
                self._release_task_pins(task)
        self._pump(key, state)

    _REPLY_EVENT = {"ok": "FINISHED", "cancelled": "CANCELLED"}

    def _absorb_reply_refs(self, task_id: bytes, reply, *, discard: bool):
        """Absorb a successful reply's reference bookkeeping — shared by
        the normal ok path and the deadline-expired straggler path.
        Neither may skip it: the worker registered borrows and
        escape-pinned nested refs during serialization, so dropping the
        records would free objects the worker still holds, or leak pins
        forever.  Borrow registration must precede the caller's
        _release_task_pins so a stored arg ref keeps its object pinned
        across the handoff.  With discard=True the value can never be
        read (its returns were already resolved to an error), so every
        nested set is released after the escape-pin grace instead of
        being recorded as contained."""
        # In-band borrow registration (see worker_main: reply["borrows"]).
        for oid, epoch in reply.get("borrows", []):
            self.reference_counter.add_borrower_from_reply(
                bytes(oid), bytes(reply["borrower_id"]), epoch=epoch)
        for i, entry in enumerate(reply["returns"]):
            # ObjectID.for_task_return without the class round-trips:
            # ids are plain concatenation (ids.py:166).
            oid = task_id + (i + 1).to_bytes(4, "little")
            # Refs nested inside this return value: the worker already
            # escape-pinned each at its owner during serialization; we
            # record containment so freeing the return releases them
            # (reference: task replies carry borrowed-ref metadata).
            nested = [(bytes(noid),
                       None if tuple(nowner) == self.address
                       else tuple(nowner))
                      for noid, nowner in entry.get("nested", [])]
            # Nested refs WE own arrive unpinned by protocol (the worker
            # defers to us to avoid the notify-vs-reply socket race);
            # take their escape pins now, strictly before the submitted
            # arg pins are released by the caller.
            for noid, nowner in nested:
                if nowner is None:
                    self.reference_counter.add_escape_pin(noid)
            if nested and (discard
                           or not self.reference_counter.is_tracked(oid)):
                # Value discarded, or container already freed (caller
                # dropped the return ref mid-flight): release the
                # worker-taken pins instead of recording them forever.
                # Delayed so in-flight escape_pin notifies land first.
                self.loop.call_later(
                    1.0, lambda n=nested: self._release_nested(n))
            elif nested:
                self._record_contained(oid, nested, take_pins=False)

    def _handle_reply(self, spec, task: Optional[_PendingTask], reply):
        task_id = spec["task_id"]
        self._disarm_task_deadline(task_id)
        if task_id in self._deadline_expired:
            # The owner-side deadline already resolved the returns with
            # DeadlineExceededError; this straggler reply (or the chased
            # cancel's ack) only settles bookkeeping — storing its value
            # now would un-error refs the user may have already observed.
            # The bookkeeping is NOT skippable though: a successful
            # straggler registered borrows and escape-pinned nested refs
            # during serialization; dropping those records would free
            # objects the worker still holds, or leak pins forever.
            if reply.get("status") == "ok":
                self._absorb_reply_refs(task_id, reply, discard=True)
            self._deadline_expired.discard(task_id)
            self._release_task_pins(task)
            self._cancelled.discard(task_id)
            self.record_task_event(
                task_id, spec.get("name") or spec.get("method", ""),
                "FAILED")
            return
        self.record_task_event(
            task_id, spec.get("name") or spec.get("method", ""),
            self._REPLY_EVENT.get(reply.get("status"), "FAILED"))
        if reply.get("status") == "ok":
            self._absorb_reply_refs(task_id, reply, discard=False)
            for i, entry in enumerate(reply["returns"]):
                oid = task_id + (i + 1).to_bytes(4, "little")
                if "inline" in entry:
                    self.memory_store.put_inline(oid, entry["inline"])
                else:
                    self.memory_store.put_plasma_location(
                        oid, entry["plasma"], size=entry.get("size"))
        elif reply.get("status") == "cancelled":
            self._store_task_exception(
                spec, exc.TaskCancelledError(f"{spec['name']} cancelled"))
        else:
            err = get_context().loads_code(reply["error"])
            if isinstance(err, (exc.DeadlineExceededError,
                                exc.OverloadedError,
                                exc.StreamBrokenError,
                                exc.KVGatherError)):
                # Worker-side expiry (refused-before-execution, or a
                # nested hop's budget ran out inside user code),
                # serving load-shed, and mid-stream KV-plane breaks
                # all surface TYPED — wrapped in RayTaskError they
                # would slip past the `except DeadlineExceededError` /
                # `except OverloadedError` / `except
                # StreamBrokenError` contracts the docs promise.
                self._store_task_exception(spec, err)
            else:
                wrapped = exc.RayTaskError(
                    f"task {spec['name']} failed", cause=err,
                    remote_traceback=reply.get("traceback", ""))
                self._store_task_exception(spec, wrapped)
        self._release_task_pins(task)
        self._cancelled.discard(task_id)

    def _store_task_failure(self, spec, error: Exception):
        self._store_task_exception(spec, error)

    def _release_nested(self, nested):
        for noid, nowner in nested:
            if nowner is None:
                self.reference_counter.release_escape_pin(noid)
            else:
                self._notify_owner(nowner, "escape_release", noid)

    def _release_task_pins(self, task: Optional[_PendingTask]):
        if task is None:
            return
        for oid in task.ref_args:
            self.reference_counter.remove_submitted(oid)
        task.ref_args = []
        for noid, nowner in task.borrowed_args:
            self._notify_owner(nowner, "escape_release", noid)
        task.borrowed_args = []

    def _store_task_exception(self, spec, error):
        # Terminal for every failure path (retry exhaustion, cancel,
        # recovery): the armed deadline must not fire afterwards.
        self._disarm_task_deadline(spec["task_id"])
        if spec["task_id"] in self._deadline_expired \
                and not isinstance(error, exc.DeadlineExceededError):
            # The deadline watchdog already resolved the returns with the
            # typed DeadlineExceededError; the cancel it kicked off (or a
            # racing failure path) must not downgrade that to a generic
            # TaskCancelledError/WorkerCrashedError.
            return
        data = protocol.concat_parts(get_context().serialize(error))
        for i in range(spec["nreturns"]):
            oid = ObjectID.for_task_return(
                TaskID(spec["task_id"]), i + 1).binary()
            self.memory_store.put_inline(oid, data, is_exception=True)
        if spec.get("streaming"):
            self._stream_on_task_failed(spec)

    # ------------------------------------------------- deadline watchdog ----
    def _arm_task_deadline(self, spec) -> None:
        """Loop-thread only: schedule the owner-side deadline for a spec
        submitted with .options(timeout_s=...).  The watchdog — not any
        per-hop RPC timeout — is what guarantees the user-visible bound:
        even a fully blackholed worker/agent cannot hold the returns
        hostage past the budget (they resolve to DeadlineExceededError
        and a best-effort cancel chases the in-flight attempt)."""
        dl = spec.get("deadline")
        if not dl:
            return
        self._deadline_timers[spec["task_id"]] = self.loop.call_later(
            max(0.0, dl - time.time()), self._on_task_deadline, spec)

    def _disarm_task_deadline(self, task_id: bytes) -> None:
        """The task resolved (value, error, or cancellation): its armed
        deadline must never fire — a late firing would write error
        entries for return ids whose real entries may already be freed,
        resurrecting them forever."""
        h = self._deadline_timers.pop(task_id, None)
        if h is not None:
            h.cancel()

    def _on_task_deadline(self, spec) -> None:
        tid = spec["task_id"]
        self._deadline_timers.pop(tid, None)
        oid0 = tid + (1).to_bytes(4, "little")
        if self._shutdown or self.memory_store.contains(oid0):
            return                      # resolved (or errored) in time
        name = spec.get("name") or spec.get("method", "")
        err = exc.DeadlineExceededError(
            f"task {name} exceeded its end-to-end deadline "
            f"(submitted with timeout_s; deadline passed "
            f"{time.time() - spec['deadline']:.2f}s ago)")
        self._deadline_expired.add(tid)
        # EVERY return id is consulted, not just the first: a caller
        # that dropped r0 of a multi-return task but still holds r1
        # must see r1 resolve to the typed error — else its get() hangs
        # forever, the exact outcome timeout_s exists to prevent.
        if any(self.reference_counter.is_tracked(
                    tid + (i + 1).to_bytes(4, "little"))
               for i in range(spec.get("nreturns", 1))):
            self._store_task_exception(spec, err)
        # else: the caller already dropped every return ref — storing
        # error entries nobody can observe (or free) would leak them;
        # the chase below still stops the wasted attempt.
        # Bounded: entries clear when the straggler reply/cancel lands
        # (_handle_reply) or via the sweep below once no reply can
        # arrive any more.  The chase's _cancelled entry gets the same
        # sweep — under a permanent blackhole no reply ever arrives to
        # discard it.
        self.loop.call_later(300.0, self._sweep_expired_marker, tid)
        self._spawn(self._chase_expired_task(tid))

    def _sweep_expired_marker(self, tid: bytes) -> None:
        """Cleanup for a deadline-expired task's markers.  While the
        attempt is still in flight on a live conn the marker must
        SURVIVE: discarding it early would let a >300s-late straggler
        reply (gray link, not a dead worker) take the normal ok path
        and store its value — un-erroring returns the user already
        observed as DeadlineExceededError.  Conn loss clears the
        in-flight records, so the next sweep collects; memory stays
        bounded by the in-flight set itself."""
        live = (tid in self._inflight_tasks
                or tid in self._inflight_actor_tasks
                or tid in self._resolving
                # Still queued (lease-starved task / actor call behind a
                # long predecessor): the dispatch-time _cancelled reap
                # needs the marker when the attempt finally surfaces.
                or any(t.spec["task_id"] == tid
                       for state in self._keys.values()
                       for t in state.queue)
                or any(spec["task_id"] == tid
                       for astate in self._actors.values()
                       for spec, _t, _b in astate.submit_queue))
        if live:
            self.loop.call_later(300.0, self._sweep_expired_marker, tid)
            return
        self._deadline_expired.discard(tid)
        self._cancelled.discard(tid)

    async def _chase_expired_task(self, tid: bytes) -> None:
        """Best-effort cancel of the expired attempt so a merely-slow
        (not dead) worker stops burning time on a result nobody will
        read.  (Not routed through _cancel(): the returns already
        resolved, which _cancel treats as nothing-to-do.)  Queued-but-
        undispatched attempts are reaped by the _cancelled check at
        dispatch; failures here are irrelevant."""
        self._cancelled.add(tid)
        fin = self._resolving.pop(tid, None)
        if fin is not None and not fin.done():
            fin.cancel()                # still resolving deps: never runs
            return
        # Queued at the owner but not yet dispatched (lease starvation):
        # reap NOW — the returns already resolved, so the attempt must
        # neither burn a worker later nor outlive the marker sweep and
        # store a straggler value over the typed error.
        for state in self._keys.values():
            for t in list(state.queue):
                if t.spec["task_id"] == tid:
                    state.queue.remove(t)
                    self._release_task_pins(t)
                    self._cancelled.discard(tid)
                    return
        try:
            lease = self._inflight_tasks.get(tid)
            if lease is not None and not lease.conn.closed:
                await lease.conn.call(
                    "cancel_task", {"task_id": tid, "force": False},
                    timeout=10)
                return
            astate = self._inflight_actor_tasks.get(tid)
            if astate is not None and astate.conn \
                    and not astate.conn.closed:
                # interrupt_running=False: an actor method (sync OR
                # async) already executing finishes its work and the
                # straggler result is discarded (documented contract) —
                # interrupting mid-method could leave actor state
                # half-mutated.  Queued/unstarted attempts are still
                # reaped.
                await astate.conn.call(
                    "cancel_task", {"task_id": tid, "force": False,
                                    "interrupt_running": False},
                    timeout=10)
        except (rpc.RpcError, asyncio.TimeoutError):
            pass

    # -------------------------------------------------------------- cancel ---
    def cancel(self, ref: ObjectRef, force: bool = False):
        return self._run(self._cancel(ref.binary(), force))

    async def _cancel(self, oid: bytes, force: bool) -> bool:
        """Cancel the task that creates `oid` (reference: core_worker.h
        CancelTask / CancelRemoteTask, core_worker.proto:531). Queued tasks
        resolve immediately to TaskCancelledError; running async actor
        tasks get their coroutine cancelled; running sync tasks get an
        async-exc (or force=True worker kill)."""
        if ObjectID(oid).is_put():
            raise TypeError(
                "cancel() expects a task return ref, not a put() ref "
                "(reference: ray.cancel only cancels tasks)")
        if self.memory_store.contains(oid):
            return False   # already resolved: nothing to cancel
        task_id = ObjectID(oid).task_id().binary()
        astate = self._inflight_actor_tasks.get(task_id)
        if force and astate is not None:
            raise ValueError(
                "force=True is not supported for actor tasks (it would kill "
                "the whole actor); use ray_tpu.kill(actor) instead")
        self._cancelled.add(task_id)
        # Still resolving dependencies: cancel the deferred-submission
        # coroutine; its CancelledError path stores TaskCancelledError.
        # A done entry means the task moved on (enqueued/pushed) — fall
        # through to the queue/in-flight paths below.
        fin = self._resolving.pop(task_id, None)
        if fin is not None and not fin.done():
            self._cancelled.discard(task_id)
            fin.cancel()
            return True
        # Still queued at the owner: drop it before it ever dispatches.
        for state in self._keys.values():
            for t in list(state.queue):
                if t.spec["task_id"] == task_id:
                    state.queue.remove(t)
                    self._store_task_exception(
                        t.spec,
                        exc.TaskCancelledError(f"{t.spec['name']} cancelled"))
                    self._release_task_pins(t)
                    self._cancelled.discard(task_id)
                    return True
        # In flight on a leased worker.
        lease = self._inflight_tasks.get(task_id)
        if lease is not None and not lease.conn.closed:
            try:
                return bool(await lease.conn.call(
                    "cancel_task", {"task_id": task_id, "force": force},
                    timeout=10))
            except (rpc.RpcError, asyncio.TimeoutError):
                return True  # worker died mid-cancel: resolves as cancelled
        # In flight on an actor.
        if astate is not None and astate.conn and not astate.conn.closed:
            try:
                return bool(await astate.conn.call(
                    "cancel_task", {"task_id": task_id, "force": False},
                    timeout=10))
            except (rpc.RpcError, asyncio.TimeoutError):
                return True
        # Not visible yet (actor resolving, push racing): the _cancelled
        # mark is honored at dispatch by _push_batch/_push_actor_task.
        return True

    # ------------------------------------------------------------- actors ----
    def _on_loop_thread(self) -> bool:
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False

    def create_actor(self, *, cls, actor_id: bytes, args, kwargs, resources,
                     name=None, get_if_exists=False, max_restarts=0,
                     max_concurrency=1, runtime_env=None,
                     scheduling_strategy=None, class_name="",
                     concurrency_groups=None) -> dict:
        # Class + args serialize on the CALLING thread (post-call mutation
        # of init args is safe; matches submit_actor_task's guarantee).
        if not self._on_loop_thread():
            runtime_env = self.package_runtime_env_cached(runtime_env)
        elif runtime_env and (runtime_env.get("working_dir")
                              or runtime_env.get("py_modules")):
            raise RuntimeError(
                "working_dir/py_modules packaging uploads to the GCS and "
                "cannot run on the event loop; create this actor from a "
                "sync context (or pre-package the runtime_env)")
        ctx = get_context()
        blob = ctx.dumps_code(cls)
        arg_entries, ref_args, borrowed_args, big_puts = \
            self._build_arg_entries_sync(args, kwargs)
        coro = self._create_actor(
            blob=blob, actor_id=actor_id, arg_entries=arg_entries,
            ref_args=ref_args, borrowed_args=borrowed_args,
            big_puts=big_puts,
            resources=resources, name=name, get_if_exists=get_if_exists,
            max_restarts=max_restarts, max_concurrency=max_concurrency,
            concurrency_groups=concurrency_groups,
            runtime_env=runtime_env, scheduling_strategy=scheduling_strategy,
            class_name=class_name)
        if self._on_loop_thread():
            # Called from an async actor method (e.g. a controller creating
            # replicas): registration proceeds in the background and the
            # client-minted id is returned immediately. get_if_exists needs
            # the existing actor's id synchronously, which would block the
            # loop — disallowed here.
            if get_if_exists:
                raise RuntimeError(
                    "get_if_exists=True cannot be used from an async actor "
                    "method; create the actor from a sync method")
            fut = self._spawn(coro)
            self._registering[actor_id] = fut

            def _done(f, aid=actor_id):
                self._registering.pop(aid, None)
                if not f.cancelled() and f.exception():
                    logger.error(
                        "background actor registration for %s failed: %s",
                        class_name, f.exception())
            fut.add_done_callback(_done)
            return {"actor_id": actor_id, "class_name": class_name}
        return self._run(coro)

    async def _create_actor(self, *, blob, actor_id, arg_entries, ref_args,
                            borrowed_args, big_puts, resources,
                            name, get_if_exists, max_restarts, max_concurrency,
                            runtime_env, scheduling_strategy, class_name,
                            concurrency_groups=None):
        cls_id = protocol.function_id(blob)
        try:
            await self._store_big_puts(arg_entries, big_puts)
            await self.gcs.call("kv_put", {"ns": "actor_cls",
                                           "key": cls_id.hex(),
                                           "value": blob, "overwrite": False})
            return await self._register_actor_spec({
                "actor_id": actor_id,
                "job_id": self.job_id,
                "class_id": cls_id,
                "class_name": class_name,
                "args": arg_entries,
                "resources": resources,
                "name": name,
                "get_if_exists": get_if_exists,
                "max_restarts": max_restarts,
                "max_concurrency": max_concurrency,
                "concurrency_groups": concurrency_groups or {},
                "runtime_env": runtime_env,
                "scheduling_strategy": scheduling_strategy,
                "owner_addr": list(self.address),
            })
        finally:
            # Init-arg pins live until registration settles (the actor's
            # __init__ runs before register_actor returns).
            for oid in ref_args:
                self.reference_counter.remove_submitted(oid)
            for noid, nowner in borrowed_args:
                self._notify_owner(nowner, "escape_release", noid)

    async def _register_actor_spec(self, spec):
        # Epoch-stamped mutation: a fenced ex-primary (or a primary that
        # failed over past us) rejects this typed instead of recording a
        # placement nobody will honor.  A lagging-but-legitimate owner
        # (we just hadn't heard about the failover yet) refreshes its
        # epoch and resubmits ONCE — registration is an id-keyed upsert,
        # so the retry is exactly-once.
        res = await self._gcs_mutate("register_actor", {"spec": spec},
                                     timeout=180)
        return res["actor"]

    async def _gcs_mutate(self, method, payload, timeout=None):
        """Issue an epoch-stamped GCS mutation; on a stale-epoch
        rejection, learn the current epoch and retry once.  Raises
        StaleEpochError if the refreshed epoch is STILL refused — that
        means this owner is genuinely fenced off, not merely behind."""
        payload = dict(payload)
        for attempt in range(2):
            payload[protocol.EPOCH_KEY] = self.cluster_epoch
            try:
                return await self.gcs.call(method, payload, timeout=timeout)
            except rpc.RpcError as e:
                if "stale_epoch" not in str(e) or attempt:
                    if "stale_epoch" in str(e):
                        self.stale_epoch_rejections += 1
                        raise exc.StaleEpochError(
                            f"GCS refused {method}: {e}",
                            stale_epoch=self.cluster_epoch) from e
                    raise
                self.stale_epoch_rejections += 1
                try:
                    info = await self.gcs.call("get_cluster_info", {})
                    self._learn_epoch(info.get(protocol.EPOCH_KEY))
                except rpc.RpcError:
                    pass

    def _build_arg_entries_sync(self, args, kwargs):
        """Serialize args on the CALLING thread (so post-call mutation is
        safe) without touching the event loop: ObjectRefs pass by
        reference, small values inline, oversized values are assigned a
        put id whose plasma store happens later on the loop (big_puts).
        Owned refs get submitted pins here; borrowed nested refs get
        escape pins at their owners. Returns (entries, ref_args,
        borrowed_args, big_puts)."""
        ctx = get_context()
        entries: List[dict] = []
        ref_args: List[bytes] = []
        borrowed_args: List[tuple] = []
        big_puts: List[tuple] = []   # (oid, parts) — stored by the coroutine
        items = [("", a) for a in args] + list(kwargs.items())
        for kw, a in items:
            if isinstance(a, ObjectRef):
                oid = a.binary()
                owner = list(a.owner_address or self.address)
                hint, sz, dev, dsk = None, None, None, None
                if tuple(owner) == self.address:
                    entry_ms = self.memory_store.get(oid)
                    if entry_ms is not None:
                        if entry_ms.plasma_node:
                            # Full replica set (primary first, suspects
                            # last): the scheduler scores bytes-already-
                            # local against EVERY holder and the
                            # executing node's prefetch stripes across
                            # them.
                            hint = self._ordered_locations(entry_ms)
                            sz = entry_ms.size
                        if entry_ms.device_nodes:
                            # Device-tier holders ride a SEPARATE hint
                            # key: arg_locality scores them local-or-
                            # better, but they never join the pull
                            # sources in ref[2] (device bytes aren't in
                            # any arena).
                            dev = [list(x) for x in entry_ms.device_nodes]
                            if sz is None:
                                sz = entry_ms.size or (
                                    len(entry_ms.data)
                                    if entry_ms.data is not None else None)
                        if entry_ms.disk_nodes:
                            # Storage-tier holders (spilled copy on local
                            # NVMe): arg_locality scores them between
                            # arena-local and remote — restoring from the
                            # spill file beats a network pull.
                            dsk = [list(x) for x in entry_ms.disk_nodes]
                # Pin EVERY by-ref arg while in flight — for borrowed refs
                # the submitted pin keeps the local borrow registered (and
                # thus the owner's borrower entry) until the reply.
                ref_args.append(oid)
                self.reference_counter.add_submitted(oid)
                entry = {"ref": [oid, owner, hint]}
                if sz:
                    entry["sz"] = sz
                if dev:
                    entry["dev"] = dev
                if dsk:
                    entry["dsk"] = dsk
            else:
                ctx.capture = captured = []
                try:
                    parts = ctx.serialize(a)
                finally:
                    ctx.capture = None
                size = ctx.total_size(parts)
                for noid, nowner in captured:
                    if nowner is None:
                        ref_args.append(noid)
                        self.reference_counter.add_submitted(noid)
                    else:
                        self._notify_owner(nowner, "escape_pin", noid)
                        borrowed_args.append((noid, nowner))
                if size <= self._inline_limit:
                    entry = {"v": protocol.concat_parts(parts)}
                else:
                    poid = self._next_put_id()
                    self.reference_counter.add_owned(poid)
                    self.reference_counter.add_submitted(poid)
                    ref_args.append(poid)
                    if not self._on_loop_thread() and \
                            self._put_store_sync(poid, parts):
                        # Zero-copy: one sync memcpy into shm right here —
                        # post-call arg mutation is safe (the copy already
                        # happened) and no bytes() flatten survives.
                        self.memory_store.put_plasma_location(
                            poid, list(self.agent_address), size=size)
                        entry = {"ref": [poid, list(self.address),
                                         [list(self.agent_address)]],
                                 "sz": size}
                    else:
                        # Arena full (or submitting from the loop thread,
                        # which must not carry the memcpy): the store
                        # happens later on the loop — so the parts must
                        # be detached from the caller's mutable buffers.
                        big_puts.append(
                            (poid, [bytes(p) for p in parts]))
                        entry = {"ref": [poid, list(self.address), None]}
            if kw:
                entry["kw"] = kw
            entries.append(entry)
        return entries, ref_args, borrowed_args, big_puts

    async def _store_big_puts(self, spec_args, big_puts):
        """Plasma-store oversized sync-serialized args and stamp their
        location hints into the spec entries."""
        for poid, parts in big_puts:
            await self._put_plasma(poid, parts)
            entry_ms = self.memory_store.get(poid)
            for e in spec_args:
                if "ref" in e and bytes(e["ref"][0]) == poid:
                    e["ref"][2] = [list(self.agent_address)]
                    if entry_ms is not None and entry_ms.size:
                        e["sz"] = entry_ms.size

    def submit_actor_task(self, *, actor_id: bytes, method: str, args, kwargs,
                          num_returns, max_task_retries: int = 0,
                          generator_backpressure: int = 0,
                          out_of_order: bool = False,
                          timeout_s: Optional[float] = None
                          ) -> List[ObjectRef]:
        """Sync-safe from ANY thread, including the event loop (async actor
        methods submitting to other actors — e.g. a Serve controller
        pinging replicas). Args are serialized synchronously on the calling
        thread (so post-call mutation of them is safe, matching reference
        semantics); only plasma puts for oversized values and the push
        itself run as a scheduled coroutine."""
        if self.loop is None:
            raise RuntimeError("core worker not started")
        num_returns, streaming = self._parse_streaming(
            num_returns, generator_backpressure)
        state = self._actors.get(actor_id)
        if state is None:
            state = self._actors.setdefault(actor_id, _ActorState(actor_id))
        if out_of_order:
            state.out_of_order = True
        task_id = fast_actor_task_id(actor_id)
        if not args and not kwargs:
            # No-arg fast branch (ping/poll-style calls dominate fan-out
            # load; skips the arg-entry walk entirely).
            entries, ref_args, borrowed_args, big_puts = [], [], [], []
        else:
            entries, ref_args, borrowed_args, big_puts = \
                self._build_arg_entries_sync(args, kwargs)
        with self._seq_lock:
            state.seq += 1
            seq = state.seq
        # `is not None`, not truthiness: timeout_s=0 is an already-
        # exhausted budget (e.g. max(0, remaining)) and must expire
        # typed immediately, not silently run unbounded.
        deadline = (time.time() + timeout_s) if timeout_s is not None \
            else deadlines.get()
        spec = protocol.make_task_spec(
            task_id=task_id, job_id=self.job_id, fn_id=b"", args=entries,
            nreturns=num_returns, owner_addr=list(self.address), resources={},
            retries_left=max_task_retries,
            actor_id=actor_id, method=method, seq=seq, name=method,
            streaming=streaming, deadline=deadline)
        refs = []
        for i in range(num_returns):
            oid = task_id + (i + 1).to_bytes(4, "little")
            self.reference_counter.add_owned(oid)
            refs.append(ObjectRef(oid, self.address, worker=self))
        if streaming is not None:
            self.register_stream(task_id, streaming["bp"],
                                 expected_attempt=max_task_retries)
            refs = [ObjectRefGenerator(self, task_id, refs[0])]
        task = _PendingTask(spec, ref_args, borrowed_args)
        self.record_task_event(task_id, method, "SUBMITTED")

        def _go():
            state.submit_queue.append((spec, task, big_puts))
            self._arm_task_deadline(spec)
            self._schedule_actor_drain(state)

        if self._on_loop_thread():
            _go()
        else:
            self._post_to_loop(_go)
        return refs

    def _schedule_actor_drain(self, state: _ActorState):
        """Defer the queue drain to the END of the current loop tick so a
        submission burst (e.g. 200 .remote() calls landing as consecutive
        callbacks) accumulates and leaves as a handful of multi-call frames
        instead of 200 singles. An eager drain-per-submission would always
        see a 1-element queue."""
        if state.drain_scheduled or state.draining:
            return
        state.drain_scheduled = True

        def _kick():
            state.drain_scheduled = False
            if not state.draining and state.submit_queue:
                self._spawn(self._drain_actor_queue(state))

        self.loop.call_soon(_kick)

    async def submit_actor_task_async(self, *, actor_id, method, args, kwargs,
                                      num_returns, max_task_retries: int = 0,
                                      generator_backpressure: int = 0
                                      ) -> List[ObjectRef]:
        return self.submit_actor_task(
            actor_id=actor_id, method=method, args=args, kwargs=kwargs,
            num_returns=num_returns, max_task_retries=max_task_retries,
            generator_backpressure=generator_backpressure)

    _ACTOR_PUSH_BATCH = 256

    async def _drain_actor_queue(self, state):
        """Drains the per-actor queue in submission order: awaiting the
        plasma puts happens inside the drain, and each push is scheduled
        (not awaited) so concurrent calls still pipeline to async actors.

        Specs that are push-ready without awaiting anything (no plasma
        puts, no ref args — the fan-out hot path) accumulate and go out as
        ONE multi-call frame (rpc.call_many): each sub-call still
        dispatches and replies independently on the worker, so semantics
        match per-call pushes, but framing costs amortize (~4x fewer
        cycles/call under load; reference: actor_task_submitter.cc sends
        per-task gRPC but amortizes in C++ — batching is the Python-plane
        equivalent)."""
        if state.draining:
            return
        state.draining = True
        batch: list = []

        def _flush():
            if not batch:
                return
            items, batch[:] = list(batch), []
            self._spawn(self._push_actor_batch(state, items))

        try:
            while state.submit_queue:
                spec, task, big_puts = state.submit_queue.popleft()
                if not big_puts and not any(
                        "ref" in e for e in spec["args"]):
                    batch.append((spec, task))
                    if len(batch) >= self._ACTOR_PUSH_BATCH:
                        _flush()
                    continue
                # Slow path (plasma puts / ref-arg resolution may suspend):
                # flush what's accumulated first so ready pushes aren't
                # gated behind this item's awaits.
                _flush()
                if state.out_of_order:
                    # Out-of-order submit queue (reference:
                    # out_of_order_actor_submit_queue.cc, opted into via
                    # allow_out_of_order_execution): this call resolves
                    # its deps OFF the drain, so later calls whose deps
                    # are already ready are not head-of-line blocked
                    # behind it.  Only meaningful for actors that execute
                    # concurrently anyway (async / max_concurrency>1).
                    self._spawn(
                        self._resolve_and_push_actor_task(state, spec,
                                                          task, big_puts))
                    continue
                if not await self._resolve_task_args(spec, task,
                                                           big_puts):
                    continue
                self._spawn(
                    self._push_actor_task(state, spec, task))
            _flush()
        finally:
            state.draining = False
            # Submissions that raced the final drain iteration (appended
            # after the while-check) restart the drain.
            if state.submit_queue:
                self._schedule_actor_drain(state)

    async def _resolve_task_args(self, spec, task, big_puts) -> bool:
        """Submitter-side dependency resolution for owned ref args, shared
        by normal-task and actor-task submission (reference:
        dependency_resolver.cc — the task is not pushed until its deps
        exist): pending results are awaited, small values inlined, plasma
        locations stamped.  Keeps the callee's execution slot free while
        deps materialize and removes the callee-side fetch timeout from
        the path.  Returns False (task failed) on a put/resolve error."""
        try:
            await self._store_big_puts(spec["args"], big_puts)
            for e in spec["args"]:
                if "ref" not in e:
                    continue
                roid = bytes(e["ref"][0])
                if tuple(e["ref"][1]) != self.address:
                    continue   # borrowed: callee resolves via owner
                if e["ref"][2] is not None:
                    continue   # already has a plasma location
                entry = await self.memory_store.wait_for(roid)
                if entry.data is not None:
                    val = {"v": entry.data}
                    if "kw" in e:
                        val["kw"] = e["kw"]
                    e.clear()
                    e.update(val)
                elif entry.plasma_node is not None:
                    e["ref"][2] = self._ordered_locations(entry)
                    if entry.size:
                        e["sz"] = entry.size
        except Exception as e:  # put/resolve failed: fail this task
            self._store_task_exception(spec, exc.RayError(
                f"failed to resolve task arg: {e}"))
            self._release_task_pins(task)
            return False
        return True

    async def _resolve_and_push_actor_task(self, state, spec, task,
                                           big_puts):
        """Out-of-order path: resolve deps independently, push when
        ready."""
        if await self._resolve_task_args(spec, task, big_puts):
            await self._push_actor_task(state, spec, task)

    async def _actor_conn(self, state: _ActorState) -> rpc.Connection:
        if state.conn is not None and not state.conn.closed:
            return state.conn
        if state.resolving is not None:
            await state.resolving
            if state.conn is not None and not state.conn.closed:
                return state.conn
        state.resolving = asyncio.get_running_loop().create_future()
        try:
            reg = self._registering.get(state.actor_id)
            if reg is not None:
                # This process kicked off the registration (loop-thread
                # create_actor): wait for it instead of a bounded GCS poll
                # — oversized init args can take arbitrarily long to store.
                try:
                    await asyncio.shield(reg)
                except Exception as e:
                    raise exc.ActorDiedError(
                        f"actor registration failed: {e}") from None
            for attempt in range(60):
                info = await self.gcs.call(
                    "get_actor", {"actor_id": state.actor_id,
                                  "wait_alive": True}, timeout=60)
                if info is None:
                    # The handle may have been minted before its background
                    # registration reached the GCS (loop-thread create_actor
                    # returns immediately); give registration a grace window
                    # before declaring the actor dead.
                    if attempt < 59:
                        await asyncio.sleep(0.25)
                        continue
                    raise exc.ActorDiedError("actor was never registered")
                if info["state"] == protocol.ACTOR_DEAD:
                    state.dead = True
                    state.death_cause = info.get("death_cause") or "dead"
                    raise exc.ActorDiedError(state.death_cause)
                if info["state"] == protocol.ACTOR_ALIVE and info["address"]:
                    try:
                        conn = await rpc.connect(
                            tuple(info["address"]), name="cw->actor",
                            retries=3, on_close=self._on_peer_conn_close)
                        conn.fast_handlers["complete_batch"] = \
                            self._f_complete_batch
                        state.conn = conn
                        state.address = tuple(info["address"])
                        return state.conn
                    except rpc.ConnectionLost:
                        pass
                await asyncio.sleep(0.25)
            raise exc.ActorDiedError("timed out resolving actor address")
        finally:
            fut, state.resolving = state.resolving, None
            fut.set_result(None)

    def _sweep_cancelled_actor(self, tasks):
        """Resolve any cancelled calls in `tasks`; returns the rest."""
        still = []
        for task in tasks:
            tid = task.spec["task_id"]
            if tid in self._cancelled:
                self._store_task_exception(task.spec, exc.TaskCancelledError(
                    f"{task.spec['method']} cancelled"))
                self._release_task_pins(task)
                self._cancelled.discard(tid)
            else:
                still.append(task)
        return still

    async def _push_actor_batch(self, state: _ActorState, items):
        """Push a burst of ready actor calls as one submit_batch frame
        (pre-encoded prefix + per-call deltas); results return as
        coalesced complete_batch frames on the same connection.

        Same per-call semantics as _push_actor_task (cancel checks, retry
        across restarts per retries_left, death-cause reporting — see
        _actor_tasks_lost) — only the framing and completion plumbing are
        shared.  The worker enqueues the batch in frame order onto the
        same serial queue per-call pushes use, so a sequential actor
        executes calls in submission order across batch boundaries."""
        if len(items) == 1:
            await self._push_actor_task(state, items[0][0], items[0][1])
            return
        tasks = [t for _s, t in items]
        while True:
            tasks = self._sweep_cancelled_actor(tasks)
            if not tasks:
                return
            try:
                conn = await self._actor_conn(state)
            except exc.ActorDiedError as e:
                for task in tasks:
                    self._store_task_exception(task.spec, e)
                    self._release_task_pins(task)
                return
            # Cancels may have landed while the connection resolved (an
            # actor restart can block _actor_conn for minutes); honor them
            # before the push, as the single-task path does.
            tasks = self._sweep_cancelled_actor(tasks)
            if not tasks:
                return
            if not conn.closed:
                break
            state.conn = None
        if state.prefix is None:
            state.prefix = protocol.spec_prefix_of(tasks[0].spec)
            state.prefix_blob = protocol.encode_prefix(state.prefix)
        t_push = time.monotonic()
        for task in tasks:
            tid = task.spec["task_id"]
            self._inflight_actor_tasks[tid] = state
            self._pending_replies[tid] = ("a", state, conn, task, t_push)
        outcome, info = await self._submit_batch_with_ack(
            conn, state.prefix, state.prefix_blob, tasks,
            actor=True, abort_label=f"actor {state.actor_id.hex()[:8]}")
        if outcome == "remote_error":
            e, pending = info
            for task in pending:
                tid = task.spec["task_id"]
                if self._pending_replies.pop(tid, None) is None:
                    continue
                self._inflight_actor_tasks.pop(tid, None)
                self._store_task_exception(task.spec, exc.RayError(
                    f"actor push failed: {e}"))
                self._release_task_pins(task)
        elif outcome == "conn_lost":
            if state.conn is conn:
                state.conn = None
            leftovers = [t for t in tasks
                         if self._pending_replies.pop(t.spec["task_id"],
                                                      None) is not None]
            if leftovers:
                await self._actor_tasks_lost(state, leftovers)

    async def _actor_tasks_lost(self, state: _ActorState, tasks):
        """The actor's connection died with these calls awaiting
        completion: honor cancels, retry per retries_left across the
        restart (re-entering through the reconnect-aware single-call
        path), and fail the rest with the GCS-recorded death cause (one
        lookup for the whole burst)."""
        death_cause = None
        for task in tasks:
            spec = task.spec
            tid = spec["task_id"]
            self._inflight_actor_tasks.pop(tid, None)
            if tid in self._cancelled:
                self._store_task_exception(spec, exc.TaskCancelledError(
                    f"{spec['method']} cancelled"))
                self._release_task_pins(task)
                self._cancelled.discard(tid)
            elif spec["retries_left"] != 0:
                # Negative = infinite (max_task_retries=-1).
                if spec["retries_left"] > 0:
                    spec["retries_left"] -= 1
                self._stream_reset_for_retry(spec)
                self._spawn(self._push_actor_task(state, spec, task))
            else:
                if death_cause is None:
                    death_cause = await self._actor_death_cause(
                        state.actor_id)
                self._store_task_exception(spec, exc.ActorDiedError(
                    f"actor {state.actor_id.hex()[:8]} died during "
                    f"{spec['method']}"
                    + (f": {death_cause}" if death_cause else "")))
                self._release_task_pins(task)

    async def _push_actor_task(self, state: _ActorState, spec, task):
        """Push with reconnect-after-restart: a ConnectionLost mid-call
        retries against the actor's next incarnation while retries_left
        lasts (reference: actor_task_submitter.cc queueing across restarts
        per max_task_retries); _actor_conn blocks through RESTARTING and
        raises once the GCS declares the actor DEAD."""
        task_id = spec["task_id"]
        while True:
            if task_id in self._cancelled:
                self._store_task_exception(
                    spec, exc.TaskCancelledError(f"{spec['method']} cancelled"))
                self._release_task_pins(task)
                self._cancelled.discard(task_id)
                return
            try:
                conn = await self._actor_conn(state)
            except exc.ActorDiedError as e:
                self._store_task_exception(spec, e)
                self._release_task_pins(task)
                return
            if task_id in self._cancelled:
                continue  # loop top resolves it as cancelled
            self._inflight_actor_tasks[task_id] = state
            try:
                # timeout=0: this per-call push's reply IS the method's
                # completion — a long-running actor method must not be
                # guillotined by the unary-call default.
                reply = await conn.call("push_actor_task", spec, timeout=0)
            except rpc.ConnectionLost:
                state.conn = None
                if task_id in self._cancelled:
                    self._store_task_exception(
                        spec, exc.TaskCancelledError(
                            f"{spec['method']} cancelled"))
                    self._release_task_pins(task)
                    self._cancelled.discard(task_id)
                    return
                if spec["retries_left"] != 0:
                    # Negative = infinite (max_task_retries=-1).
                    if spec["retries_left"] > 0:
                        spec["retries_left"] -= 1
                    self._stream_reset_for_retry(spec)
                    continue
                cause = await self._actor_death_cause(state.actor_id)
                self._store_task_exception(spec, exc.ActorDiedError(
                    f"actor {state.actor_id.hex()[:8]} died during "
                    f"{spec['method']}"
                    + (f": {cause}" if cause else "")))
                self._release_task_pins(task)
                return
            finally:
                self._inflight_actor_tasks.pop(task_id, None)
            self._handle_reply(spec, task, reply)
            return

    async def _actor_death_cause(self, actor_id: bytes) -> str:
        """Fetch the GCS-recorded death cause (e.g. the OOM monitor's
        reason) for a crashed actor.  The agent's reaper reports the death
        within its 0.5 s poll, so give the record a short grace window."""
        for i in range(8):
            try:
                info = await self.gcs.call(
                    "get_actor", {"actor_id": actor_id,
                                  "wait_alive": False}, timeout=5)
            except (rpc.RpcError, asyncio.TimeoutError):
                return ""
            if info and info.get("death_cause"):
                return info["death_cause"]
            if info and info["state"] == protocol.ACTOR_ALIVE and i >= 3:
                # Still ALIVE well past the reaper's report window: the
                # actor restarted rather than died terminally.  (Early
                # ALIVE reads just mean the death report hasn't landed.)
                return ""
            await asyncio.sleep(0.4)
        return ""

    def kill_actor(self, actor_id: bytes, no_restart=True):
        if self._on_loop_thread():
            self.kill_actor_nowait(actor_id)
        else:
            self._run(self.gcs.call("kill_actor", {"actor_id": actor_id}))
        st = self._actors.get(actor_id)
        if st:
            st.dead = True

    def kill_actor_nowait(self, actor_id: bytes):
        """Fire-and-forget termination used by handle GC — safe to call from
        __del__ on any thread, including the loop thread."""
        if self._shutdown or self.loop is None or not self.loop.is_running():
            return
        def _go():
            if self.gcs and not self.gcs.closed:
                try:
                    self.gcs.notify("kill_actor", {"actor_id": actor_id})
                except rpc.RpcError:
                    pass
        self.loop.call_soon_threadsafe(_go)

    def get_actor_info(self, *, actor_id=None, name=None):
        return self._run(self.gcs.call(
            "get_actor", {"actor_id": actor_id, "name": name,
                          "wait_alive": False}))

    async def get_actor_info_async(self, *, actor_id=None, name=None):
        """Loop-thread-safe variant for async actor methods (e.g. a Serve
        handle resolving its controller from inside a deployment)."""
        return await self.gcs.call(
            "get_actor", {"actor_id": actor_id, "name": name,
                          "wait_alive": False})
