"""Worker process entry point: executes tasks and hosts actors.

Equivalent of the reference's default_worker.py + the Cython execute_task path
(reference: python/ray/_private/workers/default_worker.py,
python/ray/_raylet.pyx:1557 execute_task, task_execution/task_receiver.cc).

The worker runs an asyncio loop serving push_task / push_actor_task RPCs from
submitters. Sync user functions run on executor threads so the loop keeps
serving (and the embedded CoreWorker can submit nested tasks); async actor
methods run as coroutines on the loop itself with a max_concurrency
semaphore (reference: fiber.h async actors + ConcurrencyGroupManager).
Normal tasks execute one-at-a-time per worker — parallelism comes from the
submitter holding many leases, as in the reference.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

from . import diagnosis, protocol, rpc
from .config import get_config
from .core_worker import CoreWorker
from .ids import ObjectID, TaskID
from .serialization import get_context
from .shm_store import StoreFullError
from .. import exceptions as exc

logger = logging.getLogger("ray_tpu.worker")

# Debug: log every push/execute with a timestamp (RAY_TPU_TRACE_EXEC=1).
_TRACE_EXEC = bool(os.environ.get("RAY_TPU_TRACE_EXEC"))


class Executor:
    def __init__(self, core: CoreWorker, agent_conn_holder):
        self.core = core
        self._fn_cache: Dict[bytes, Any] = {}
        self._fn_coro_cache: Dict[bytes, bool] = {}
        self._task_lock = asyncio.Lock()       # normal tasks: serial
        self.actor: Any = None
        self.actor_id: Optional[bytes] = None
        self._actor_sem: Optional[asyncio.Semaphore] = None
        self._group_sems: dict = {}
        self._max_concurrency = 1
        self._actor_is_async = False
        # method name -> bound sync method (or None) / is-coroutine flag:
        # avoids a getattr + iscoroutinefunction walk per call on the
        # actor hot path.
        self._sync_method_cache: Dict[str, Any] = {}
        self._coro_method_cache: Dict[str, bool] = {}
        self._fast_method_ok: Dict[str, bool] = {}  # fast-dispatch gate
        self._running: Dict[bytes, tuple] = {}  # task_id -> (task, is_async)
        self._running_threads: Dict[bytes, int] = {}  # sync task -> thread id
        self._thread_guard = threading.Lock()
        self._cancel_requested: set = set()   # cancels that arrived early
        self._cancel_intent: set = set()      # async-exc deliveries sent
        # Default-group serial queue for sync actors (chunked execution).
        self._serial_q: deque = deque()
        self._serial_draining = False
        # Normal-task queue (chunked execution under the task lock).
        self._task_q: deque = deque()
        self._task_draining = False
        # Chunks popped from the queues and currently executing: cancel
        # must still find their not-yet-started entries (see
        # _resolve_queued_cancel).
        self._active_chunks: list = []
        # Batched submit/complete fast path (f_submit_batch): decoded
        # spec prefixes keyed by their wire blob, and per-connection
        # completion buffers flushed once per loop tick.
        self._prefix_cache: Dict[bytes, dict] = {}
        self._cmpl_bufs: Dict[Any, list] = {}

    # ------------------------------------------------------------ helpers ---
    async def _load_function(self, fn_id: bytes):
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            blob = await self.core.gcs.call(
                "kv_get", {"ns": "fn", "key": fn_id.hex()})
            if blob is None:
                raise exc.RayError(f"function {fn_id.hex()} not exported")
            fn = get_context().loads_code(blob)
            self._fn_cache[fn_id] = fn
        return fn

    async def _resolve_arg_entries(self, entries):
        args, kwargs = [], {}
        ctx = get_context()
        for e in entries:
            if "v" in e:
                val = ctx.deserialize(memoryview(e["v"]))
                if isinstance(val, exc.RayError):
                    raise val
            else:
                oid, owner_addr, plasma_hint = e["ref"]
                # Through the ref factory so the worker registers as a
                # borrower with the owner (kept alive if user code stores
                # the ref beyond the task).
                ref = self.core._ref_factory(bytes(oid), tuple(owner_addr))
                # Replica-set hint (list of holders, primary first;
                # legacy peers sent one address): the local agent
                # stripes the pull across every holder and registers the
                # landed copy back with the owner — usually a no-op
                # join of the prefetch pull the lease grant started.
                locs = protocol.ref_locations(plasma_hint)
                locs = [a for a in locs if a != self.core.agent_address]
                if locs and not self.core.store.contains(bytes(oid)):
                    try:
                        await self.core.agent.call("pull_object", {
                            "object_id": bytes(oid),
                            "from_addrs": [list(a) for a in locs],
                            "owner_addr": list(owner_addr),
                            "priority": 2}, timeout=120)
                    except (rpc.RpcError, asyncio.TimeoutError):
                        pass  # owner-mediated fetch below will sort it out
                # Bounded: a freed/unrecoverable arg fails this task rather
                # than holding the worker's task lock forever.
                arg_deadline = time.monotonic() + \
                    get_config().task_arg_fetch_timeout_s
                val = await self.core._get_one(ref, arg_deadline)
            if e.get("kw"):
                kwargs[e["kw"]] = val
            else:
                args.append(val)
        return args, kwargs

    async def _serialize_value(self, oid: bytes, value, caller_addr=None):
        """Serialize ONE return/stream-item value into a reply entry:
        small -> inline bytes, large -> local shared-memory store via the
        create-backpressure path (reference: core_worker.h:1045
        AllocateReturnObject — same split).  Plasma copies are pinned via
        pin-transfer inside store_with_backpressure."""
        if value is None:
            # None is the overwhelmingly common return under fan-out load
            # (pings, fire-and-forget mutations); its pickle is constant
            # and carries no nested refs, so skip the serializer.
            return {"inline": get_context().none_blob()}
        ctx = get_context()
        ctx.capture = captured = []
        try:
            parts = ctx.serialize(value)
        finally:
            ctx.capture = None
        size = ctx.total_size(parts)
        # The serializer takes the nested-ref pins NOW — synchronously
        # for objects this worker owns (no unpinned window between the
        # reply and the submitter's bookkeeping). Refs owned by the
        # CALLER are deliberately NOT pinned here: an escape_pin notify
        # travels a different socket than the push reply and can lose
        # the race against the caller releasing its submitted arg pins,
        # freeing the object mid-handoff — instead the caller takes
        # those pins itself, synchronously, from the reply's `nested`
        # metadata (see _handle_reply). Third-party owners get the
        # notify (sent before the reply, tiny residual window).
        for noid, nowner in captured:
            if nowner is None:
                self.core.reference_counter.add_escape_pin(noid)
            elif caller_addr is None or tuple(nowner) != tuple(caller_addr):
                self.core._notify_owner(nowner, "escape_pin", noid)
        nested = [[noid, list(nowner) if nowner else
                   list(self.core.address)]
                  for noid, nowner in captured]
        if size <= self.core._inline_limit:
            entry = {"inline": protocol.concat_parts(parts)}
        else:
            # store_with_backpressure pins the plasma copy via pin-transfer
            # (owner = the CALLER for returns — its directory is the one a
            # drain migration must repoint); the size rides the reply so
            # the owner's directory entry can feed locality scoring
            # without a store round trip.
            await self.core.store_with_backpressure(
                oid, parts, owner_addr=caller_addr)
            entry = {"plasma": list(self.core.agent_address), "size": size}
        if nested:
            entry["nested"] = nested
        return entry

    async def _serialize_returns(self, task_id: bytes, nreturns: int, result,
                                 caller_addr=None):
        if nreturns == 1:
            results = [result]
        else:
            results = list(result)
            if len(results) != nreturns:
                raise ValueError(
                    f"task declared {nreturns} returns but produced "
                    f"{len(results)}")
        out = []
        for i, value in enumerate(results):
            # Layout matches ObjectID.for_task_return without the wrapper
            # round-trip (reply hot path).
            oid = task_id + (i + 1).to_bytes(4, "little")
            out.append(await self._serialize_value(oid, value, caller_addr))
        return out

    # ------------------------------------------------------ fast handlers --
    # SYNC enqueue variants registered as rpc fast_handlers: the recv loop
    # calls them inline (no Task per request) and sends the reply from a
    # done-callback on the returned future.  Semantics identical to the
    # coroutine handlers below — conditional branches fall back.

    def f_push_task(self, conn, spec):
        fut = asyncio.get_running_loop().create_future()
        self._task_q.append((spec, fut))
        if not self._task_draining:
            self._task_draining = True
            rpc.spawn(self._drain_chunked(self._task_q, "_task_draining",
                                          self._task_gate))
        return fut

    def _enqueue_serial(self, spec):
        fut = asyncio.get_running_loop().create_future()
        self._serial_q.append((spec, fut))
        if not self._serial_draining:
            self._serial_draining = True
            rpc.spawn(self._drain_chunked(self._serial_q,
                                          "_serial_draining",
                                          self._actor_gate))
        return fut

    def f_push_actor_task(self, conn, spec):
        if (self._actor_is_async or self._group_sems
                or self._max_concurrency > 1 or _TRACE_EXEC
                or spec.get("method") == "__ray_dag_serve__"):
            return rpc.FAST_FALLBACK
        if self.actor is not None:
            # Methods tagged with a concurrency group must go through
            # _sem_for_method's misconfiguration check in the slow path.
            name = spec["method"]
            ok = self._fast_method_ok.get(name)
            if ok is None:
                m = getattr(type(self.actor), name, None)
                ok = getattr(m, "__ray_concurrency_group__", None) is None
                self._fast_method_ok[name] = ok
            if not ok:
                return rpc.FAST_FALLBACK
        return self._enqueue_serial(spec)

    # --------------------------------------- batched submit/complete ----
    # submit_batch: one frame carrying a msgpack-encoded STABLE spec
    # prefix plus per-task deltas (see protocol.spec_prefix_of and
    # docs/control_plane.md).  Tasks enqueue in frame order onto the SAME
    # queues the per-call push handlers use — ordering, cancel, and
    # execution semantics are identical — and the ack returns as soon as
    # everything is enqueued.  Results ship back as coalesced
    # complete_batch frames: one notify per loop tick per connection,
    # applied owner-side in a single pass (core_worker._f_complete_batch).

    _PREFIX_CACHE_MAX = 256

    def f_submit_batch(self, conn, p):
        pr = p["pr"]
        prefix = self._prefix_cache.get(pr)
        if prefix is None:
            if len(self._prefix_cache) >= self._PREFIX_CACHE_MAX:
                self._prefix_cache.pop(next(iter(self._prefix_cache)))
            prefix = self._prefix_cache[pr] = protocol.decode_prefix(pr)
        is_actor = bool(p.get("a"))
        # Per-connection dedup of task ids: a submitter whose ack was
        # dropped (chaos / transient stall) resends the still-unfinished
        # tasks on the same connection; re-enqueueing them would run the
        # task twice and double-resolve the completion.  Bounded (resends
        # only ever target recent ids) so a long-lived connection doesn't
        # accumulate every task id it ever carried.
        seen = getattr(conn, "_seen_batch_tids", None)
        if seen is None:
            seen = conn._seen_batch_tids = (set(), deque())
        seen_set, seen_order = seen
        accepted = 0
        for d in p["t"]:
            spec = dict(prefix)
            spec.update(d)
            tid = spec["task_id"]
            if tid in seen_set:
                continue
            seen_set.add(tid)
            seen_order.append(tid)
            if len(seen_order) > 65536:
                seen_set.discard(seen_order.popleft())
            accepted += 1
            if is_actor:
                fut = self.f_push_actor_task(conn, spec)
                if fut is rpc.FAST_FALLBACK:
                    fut = rpc.spawn(self.h_push_actor_task(conn, spec))
            else:
                fut = self.f_push_task(conn, spec)
            fut.add_done_callback(
                lambda f, conn=conn, tid=tid:
                self._queue_completion(conn, tid, f))
        return {"n": accepted}

    async def h_submit_batch(self, conn, p):
        return self.f_submit_batch(conn, p)

    def _queue_completion(self, conn, tid, fut):
        try:
            reply = fut.result()
        except asyncio.CancelledError:
            reply = {"status": "cancelled"}
        except BaseException as e:  # noqa: BLE001 — infra failure must
            #                         still resolve the owner's return refs
            reply = self._error_reply(e, f"{type(e).__name__}: {e}")
        buf = self._cmpl_bufs.get(conn)
        if buf is None:
            buf = self._cmpl_bufs[conn] = []
            asyncio.get_running_loop().call_soon(
                self._flush_completions, conn)
        buf.append([tid, reply])

    def _flush_completions(self, conn):
        buf = self._cmpl_bufs.pop(conn, None)
        if not buf or conn.closed:
            return
        try:
            conn.notify("complete_batch", {"t": buf})
        except rpc.RpcError:
            pass    # owner gone; its conn-loss cleanup resolves the tasks

    # ------------------------------------------------------------ handlers --
    async def h_push_task(self, conn, spec):
        # Normal tasks execute one-at-a-time per worker; a burst pushed by
        # the submitter's per-lease multi-call frame drains through the
        # same chunked path as serial actor calls (one executor hop per
        # chunk, replies coalesced).  Single enqueue implementation: the
        # fast handler IS the path; this wrapper only awaits it.
        return await self.f_push_task(conn, spec)

    def _task_gate(self, spec):
        """Chunk-eligibility for a normal task: the cached sync function,
        or None to route through the classic singleton path (cache miss —
        _execute loads from the GCS; coroutine fn; ref args; PG-targeted
        tasks, which need the per-task placement-group context _execute
        installs for get_current_placement_group)."""
        fn_id = spec.get("fn_id")
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            return None
        # iscoroutinefunction walks code flags through unwrap() — ~8us a
        # call, paid once per fn_id instead of once per task.
        is_coro = self._fn_coro_cache.get(fn_id)
        if is_coro is None:
            is_coro = self._fn_coro_cache[fn_id] = \
                asyncio.iscoroutinefunction(fn)
        strat = spec.get("scheduling_strategy") or {}
        if (is_coro or spec.get("streaming")
                or strat.get("type") == "placement_group"
                or not all("v" in e for e in spec["args"])):
            return None
        return fn

    def _actor_gate(self, spec):
        """Chunk-eligibility for a default-group actor call: the bound
        sync method, or None (async method racing actor init, unknown
        method, ref args)."""
        if self.actor is None or spec.get("streaming"):
            return None
        name = spec["method"]
        try:
            m = self._sync_method_cache[name]
        except KeyError:
            m = getattr(self.actor, name, None)
            if m is not None and asyncio.iscoroutinefunction(m):
                m = None
            self._sync_method_cache[name] = m
        if m is None or not all("v" in e for e in spec["args"]):
            return None
        return m

    async def h_push_actor_task(self, conn, spec):
        if spec.get("method") == "__ray_dag_serve__":
            # Compiled-graph serve loop (reference: compiled_dag_node.py's
            # resident exec loop in the _ray_system concurrency group):
            # runs on its own executor thread OUTSIDE the actor's normal
            # concurrency gates, so ordinary method calls keep working
            # while the DAG is live; returns when the input channel closes.
            return await self._execute_dag_serve(spec)
        if _TRACE_EXEC:
            logger.warning("PUSH %s t=%.3f actor=%s groups=%s",
                           spec.get("method"), time.monotonic(),
                           self.actor is not None, list(self._group_sems))
        # Concurrency groups (reference: ConcurrencyGroupManager — each
        # named group has its own concurrency budget; untagged methods
        # share the default group).  A sync actor's default group is a
        # Semaphore(1), preserving serial execution order; tagged groups
        # run concurrently with it on the thread pool.
        try:
            sem = self._sem_for_method(spec["method"])
        except exc.RayError as e:
            # Misconfiguration must resolve the return ref, not poison the
            # RPC channel: ship it like any task error.
            return {"status": "error",
                    "error": get_context().dumps_code(e),
                    "traceback": str(e)}
        if self._actor_is_async:
            name = spec["method"]
            try:
                is_coro = self._coro_method_cache[name]
            except KeyError:
                import inspect as _inspect
                method = getattr(self.actor, name, None)
                # Async GENERATOR methods (streaming responses) are not
                # iscoroutinefunction but must share the async
                # concurrency budget: serializing them would run one
                # stream at a time — the opposite of continuous
                # batching, where N streams feed one engine loop.
                is_coro = method is not None and (
                    asyncio.iscoroutinefunction(method)
                    or _inspect.isasyncgenfunction(method))
                self._coro_method_cache[name] = is_coro
            if is_coro:
                async with sem:
                    return await self._execute(spec)
        if self._group_sems and sem is not self._actor_sem:
            async with sem:
                return await self._execute(spec)
        if self._actor_sem is not None and not self._actor_is_async \
                and self._max_concurrency > 1:
            # Threaded actor (reference: max_concurrency>1 on a sync
            # actor): bounded parallel execution on the thread pool.
            async with self._actor_sem:
                return await self._execute(spec)
        # Default group of a serial sync actor: run through the chunked
        # drain — a burst of queued calls executes back-to-back in ONE
        # thread-pool hop, and their replies resolve in one loop tick (so
        # the response frames coalesce into one socket write). Order is
        # the FIFO arrival order, exactly as the task-lock queue gave.
        # Shared enqueue implementation with the fast path:
        return await self._enqueue_serial(spec)

    def _sem_for_method(self, method_name: str):
        m = getattr(type(self.actor), method_name, None)
        group = getattr(m, "__ray_concurrency_group__", None)
        if group is not None:
            # A tag naming an undeclared group is a misconfiguration even
            # when the actor declares no groups at all — silently running
            # it in the default group would drop the intended isolation.
            sem = self._group_sems.get(group)
            if sem is None:
                raise exc.RayError(
                    f"method {method_name!r} declares concurrency "
                    f"group {group!r} which is not in this actor's "
                    f"concurrency_groups")
            return sem
        return self._actor_sem

    def _error_reply(self, e: BaseException, tb: str | None = None) -> dict:
        try:
            blob = get_context().dumps_code(e)
        except Exception:
            blob = get_context().dumps_code(
                exc.RayError(f"{type(e).__name__}: {e} (unpicklable)"))
        return {"status": "error", "error": blob,
                "traceback": tb or traceback.format_exc()}

    async def _drain_chunked(self, q: deque, flag: str, gate):
        """Chunked executor shared by serial sync-actor calls and normal
        tasks. Calls queued in the same burst run back-to-back in ONE
        thread-pool hop (instead of one run_in_executor round trip — two
        GIL handoffs — per call), and their replies resolve in the same
        loop tick so the response frames leave in one socket write.
        Arrival order == execution order, identical to the task-lock queue
        it replaces. gate(spec) returns the callable to run for
        chunk-eligible specs, or None to route the spec through the
        classic singleton _execute path."""
        try:
            while q:
                chunk = []
                fns = []          # gate-resolved callable per chunk item
                while q and len(chunk) < 128:
                    spec, fut = q[0]
                    fn = gate(spec)
                    if fn is None:
                        if chunk:
                            break          # run the fast chunk first
                        q.popleft()
                        # Visible to _resolve_queued_cancel while parked
                        # behind the task lock (same contract as chunks):
                        # a cancel must resolve the push reply NOW, not
                        # when the 30s predecessor releases the lock.
                        entry = [(spec, fut)]
                        self._active_chunks.append(entry)
                        try:
                            async with self._task_lock:
                                reply = await self._execute(spec)
                        except BaseException as e:  # noqa: BLE001
                            reply = self._error_reply(e)
                        finally:
                            self._active_chunks.remove(entry)
                        if not fut.done():
                            fut.set_result(reply)
                        continue
                    q.popleft()
                    chunk.append((spec, fut))
                    fns.append(fn)
                if not chunk:
                    continue
                self._active_chunks.append(chunk)
                try:
                    async with self._task_lock:
                        replies = await self._execute_chunk(chunk, fns)
                except BaseException as e:  # noqa: BLE001 — an infra
                    # failure (executor shutdown, drain cancellation) must
                    # still resolve every popped future, or the submitter's
                    # push RPCs hang forever with their lease slots held.
                    replies = [self._error_reply(e)] * len(chunk)
                finally:
                    self._active_chunks.remove(chunk)
                for (spec, fut), reply in zip(chunk, replies):
                    if not fut.done():
                        fut.set_result(reply)
        finally:
            setattr(self, flag, False)
            if q:
                # Items appended between the empty-check and this reset
                # (or left behind by an exception) restart the drain.
                setattr(self, flag, True)
                rpc.spawn(self._drain_chunked(q, flag, gate))

    async def _execute_chunk(self, chunk, fns):
        """Execute a burst of inline-arg sync functions: per-task
        bookkeeping matches _execute (events, cancel semantics, borrow
        metadata), but all user functions run in a single executor
        submission. fns[i] is the gate-resolved callable for chunk[i]
        (resolved once in the drain loop)."""
        loop = asyncio.get_running_loop()
        replies: list = [None] * len(chunk)
        runnable = []                      # (i, tid, method, args, kwargs)
        for i, (spec, _fut) in enumerate(chunk):
            tid = spec["task_id"]
            if tid in self._cancel_requested:
                self._cancel_requested.discard(tid)
                replies[i] = {"status": "cancelled"}
                continue
            dl = spec.get("deadline")
            if dl and time.time() > dl + rpc.DEADLINE_SKEW_SLACK_S:
                replies[i] = self._error_reply(
                    exc.DeadlineExceededError(
                        f"deadline exceeded before execution of "
                        f"{spec.get('name') or spec.get('method', '')}"),
                    "deadline exceeded before execution")
                continue
            # job_id from the SPEC: a pooled worker serves tasks across
            # jobs, so the process-level id would mis-attribute (and a
            # job-filtered timeline would silently lose every RUNNING).
            self.core.record_task_event(
                tid, spec.get("name") or spec.get("method", ""),
                "RUNNING", job_id=spec.get("job_id") or b"")
            try:
                if spec["args"]:
                    args, kwargs = await self._resolve_arg_entries(
                        spec["args"])
                else:
                    # No-arg fast path (ping/fan-out load): skip the
                    # resolver coroutine round trip entirely.
                    args, kwargs = (), {}
                method = fns[i]
                runnable.append((i, tid, method, args, kwargs, spec))
            except Exception as e:  # noqa: BLE001
                replies[i] = self._error_reply(e)
        if runnable:
            def _run_all():
                out = []
                prev = self.core.current_task_id
                try:
                    for _i, tid, method, args, kwargs, spec_ in runnable:
                        if tid in self._cancel_requested:
                            self._cancel_requested.discard(tid)
                            out.append(("cancelled", None))
                            continue
                        self.core.current_task_id = tid
                        self._running[tid] = (None, False)
                        try:
                            out.append(
                                ("ok", self._run_sync(tid, method, args,
                                                      kwargs, spec_)))
                        except exc.TaskCancelledError:
                            out.append(("cancelled", None))
                        except BaseException as e:  # noqa: BLE001
                            out.append(("error",
                                        (e, traceback.format_exc())))
                        finally:
                            self._running.pop(tid, None)
                finally:
                    self.core.current_task_id = prev
                return out

            outcomes = await loop.run_in_executor(self.core.executor,
                                                  _run_all)
            for (i, tid, _m, _a, _k, _s), (status, payload) in zip(
                    runnable, outcomes):
                spec = chunk[i][0]
                if status == "cancelled":
                    replies[i] = {"status": "cancelled"}
                elif status == "error":
                    e, tb = payload
                    replies[i] = self._error_reply(e, tb)
                else:
                    prev = self.core.current_task_id
                    self.core.current_task_id = tid
                    try:
                        if payload is None and spec["nreturns"] == 1:
                            # Constant wire form, no nested refs, no
                            # plasma: skip two coroutine round trips on
                            # the dominant fan-out reply shape.
                            returns = [{"inline":
                                        get_context().none_blob()}]
                        else:
                            returns = await self._serialize_returns(
                                tid, spec["nreturns"], payload,
                                caller_addr=spec.get("owner_addr"))
                        reply = {"status": "ok", "returns": returns}
                        caller = spec.get("owner_addr")
                        if caller is not None:
                            borrows = \
                                self.core.reference_counter.borrowed_from(
                                    tuple(caller))
                            if borrows:
                                reply["borrows"] = borrows
                                reply["borrower_id"] = self.core.worker_id
                        replies[i] = reply
                    except Exception as e:  # noqa: BLE001
                        replies[i] = self._error_reply(e)
                    finally:
                        self.core.current_task_id = prev
        return replies

    def _run_sync(self, task_id: bytes, fn, args, kwargs, spec=None):
        """Sync user code on an executor thread; the thread id is recorded so
        cancel_task can raise TaskCancelledError inside it (the same effect
        as the reference's SIGINT-to-worker for running tasks — lands at the
        next Python bytecode, not inside a blocking C call)."""
        with self._thread_guard:
            self._running_threads[task_id] = threading.get_ident()
        from .core_worker import task_exec_tls
        task_exec_tls.active = True     # blocking get/wait here releases CPU
        # Ambient deadline installed ON the executing thread (contextvars
        # don't cross run_in_executor): nested .remote()/get() calls made
        # by user code inherit the task's remaining end-to-end budget.
        from . import deadlines
        _dl_tok = deadlines.set_current(spec["deadline"]) \
            if spec and spec.get("deadline") else None
        try:
            if spec is not None and spec.get("trace"):
                # Span set HERE (the executing thread), not around the
                # run_in_executor call: contextvars don't cross executor
                # submission, and nested .remote() calls from user code
                # read the context from this thread.
                from ..util import tracing
                with tracing.execution_span(self.core, spec):
                    return fn(*args, **kwargs)
            return fn(*args, **kwargs)
        except exc.TaskCancelledError:
            if task_id in self._cancel_intent:
                self._cancel_intent.discard(task_id)
                raise
            # Async-exc delivery raced task turnover on a pooled thread and
            # landed on the wrong task: surface an explicit error instead
            # of a false "cancelled".
            raise exc.RayError(
                "cancellation exception delivered to an uncancelled task "
                "(thread-reuse race)") from None
        finally:
            task_exec_tls.active = False
            if _dl_tok is not None:
                deadlines.reset(_dl_tok)
            with self._thread_guard:
                self._running_threads.pop(task_id, None)

    # ------------------------------------------------- compiled-graph serve --
    async def _execute_dag_serve(self, spec):
        loop = asyncio.get_running_loop()
        try:
            args, _ = await self._resolve_arg_entries(spec["args"])
            stage = args[0]
            # Wait for actor init (the serve push can race actor_init).
            deadline = time.monotonic() + 30
            while self.actor is None and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            if self.actor is None:
                raise exc.RayError("dag serve on uninitialized actor")
            await loop.run_in_executor(
                self.core.executor, lambda: self._dag_serve(stage))
            returns = await self._serialize_returns(
                spec["task_id"], 1, None,
                caller_addr=spec.get("owner_addr"))
            return {"status": "ok", "returns": returns}
        except Exception as e:  # noqa: BLE001
            return self._error_reply(e)

    @staticmethod
    def _dag_err_body(ctx, e):
        try:
            eb = ctx.serialize(e)
        except Exception:
            eb = ctx.serialize(exc.RayError(
                f"{type(e).__name__}: {e} (unpicklable)"))
        from ..dag import _transport
        # Single-pass join: bytes.join consumes the memoryview parts
        # directly — no per-part bytes() materialization.
        return b"".join([_transport.ERR, *eb])

    def _dag_serve(self, stage):
        """Resident compiled-graph stage loop: block on input channels,
        run the bound method, write the result downstream.  Errors are
        serialized and PROPAGATED as messages (the pipeline keeps
        running); channel closure cascades a clean shutdown.  Every step
        stamps a `dag:step` span (execute→write, with the channel-wait
        time as an arg) and updates the ring-occupancy gauge — both ride
        the existing telemetry flush, nothing new per step on the wire."""
        import pickle
        from ..dag import _transport
        from . import device_plane, flight_recorder
        from .shm_store import Channel, ChannelClosed
        from ..util.metrics import Gauge
        store = self.core.store
        ctx = get_context()
        rec = flight_recorder.recorder()
        ins = [(Channel.attach(store, s["chan"]), s["reader"])
               for s in stage["in"]]
        if not ins:
            raise exc.RayError(
                "compiled DAG stage has no channel inputs (every stage "
                "must consume the InputNode or an upstream stage)")
        out = (Channel.attach(store, stage["out_chan"])
               if stage.get("out_chan") else None)
        method = getattr(self.actor, stage["method"])
        method_name = stage["method"]
        slot_bytes = stage["slot_bytes"]
        nreaders = stage.get("out_readers", 1)
        # DAG-prefixed spill ids: lets teardown sweep orphans left by a
        # writer killed between spill creation and the ring write.
        prefix = stage.get("spill_prefix")
        mint = (_transport.mint_for(prefix) if prefix
                else self.core._next_put_id)
        coll = stage.get("collective")
        # Channel-lowered collective: my contribution ring + one reader
        # per peer's ring (all local to this node — cross-node rings are
        # agent-bridged mirrors).  Pre-lowering specs carried a KV
        # rendezvous group name instead; that form is gone.
        coll_out = None
        coll_ins: list = []
        if coll:
            coll_out = Channel.attach(store, coll["out_chan"])
            coll_ins = [(Channel.attach(store, s["chan"]), s["reader"])
                        for s in coll["in"]]
        # Device transport ladder (see _private/device_plane.py):
        # local_ok (compile-time: every consumer shares this process) →
        # rung 0, the ring carries a registry token, zero host bytes.
        dev = stage.get("device") or {}
        dev_local_ok = bool(dev.get("local_ok"))
        dev_spec = dev.get("spec")
        live_tokens: list = []
        span_id = bytes(stage.get("out_chan") or stage["in"][0]["chan"])[:8]
        occ_gauge = Gauge(
            "ray_tpu_dag_ring_occupancy",
            "Compiled-DAG output-ring occupancy (messages buffered; "
            "nslots = the compile-time backpressure window)",
            tag_keys=("chan", "method"))
        occ_tags = {"chan": span_id.hex(), "method": method_name}
        consts = {}      # unpickled once
        try:
            while True:
                t_wait = rec.begin()
                recvd: list = []
                try:
                    for ch, r in ins:
                        recvd.append(_transport.recv_view(store, ch, r))
                except ChannelClosed:
                    for _b, _rel in recvd:
                        _rel()
                    break
                t0 = rec.begin()
                wait_us = max(0, (t0 - t_wait) // 1000)
                err_body = next(
                    (bytes(b) for b, _rel in recvd
                     if b[:1] == _transport.ERR), None)
                result = None
                vals = None
                if err_body is None:
                    try:
                        # Decode straight from the pinned arena views:
                        # device leaves upload from the arena (one h2d),
                        # host payloads keep the copy-out discipline.
                        vals = [device_plane.dag_decode_body(ctx, b)
                                for b, _rel in recvd]
                    except BaseException as e:  # noqa: BLE001
                        err_body = self._dag_err_body(ctx, e)
                for _b, _rel in recvd:
                    _rel()
                if err_body is None:
                    try:
                        def _arg(p):
                            kind, v = p
                            if kind == "ch":
                                return vals[v]
                            if id(p) not in consts:
                                consts[id(p)] = pickle.loads(v)
                            return consts[id(p)]

                        a = [_arg(p) for p in stage["argplan"]]
                        kw = {k: _arg(p)
                              for k, p in stage["kwargplan"].items()}
                        result = method(*a, **kw)
                        if asyncio.iscoroutine(result):
                            # Async actor method bound into the DAG: run
                            # it on the actor's event loop (this serve
                            # loop lives on an executor thread).
                            result = asyncio.run_coroutine_threadsafe(
                                result, self.core.loop).result()
                        if dev_spec is not None:
                            # Declared output contract: violations are
                            # typed per-step errors, not silent drift.
                            device_plane.validate_against_spec(
                                result, dev_spec, method_name)
                    except BaseException as e:  # noqa: BLE001
                        err_body = self._dag_err_body(ctx, e)
                if coll:
                    # Collective stages stay in LOCKSTEP even on error
                    # steps: every rank writes exactly one contribution
                    # per step (its value, or its error) and reads one
                    # from every peer — the status byte IS the ok/err
                    # flag, so flag-gather and value-exchange are ONE
                    # channel round.  The reduce runs only when all
                    # ranks are ok; otherwise every rank emits an error
                    # for this step.  Skipping the exchange on one rank
                    # would permanently desync the rings and silently
                    # pair tensors from different steps (reference:
                    # collective_node.py executes the collective
                    # unconditionally per step).
                    import numpy as np
                    try:
                        mine = (err_body if err_body is not None
                                else b"".join([_transport.OK,
                                               *ctx.serialize(
                                                   np.asarray(result))]))
                        _transport.send(store, coll_out, mine,
                                        coll["out_readers"], slot_bytes,
                                        mint)
                        peer_bodies = [_transport.recv(store, ch, r)
                                       for ch, r in coll_ins]
                        bad = next((b for b in [mine] + peer_bodies
                                    if b[:1] == _transport.ERR), None)
                        if bad is None:
                            parts = [np.asarray(result)] + [
                                ctx.deserialize(memoryview(b)[1:])
                                for b in peer_bodies]
                            from ..collective.collective import REDUCE_OPS
                            op = coll["op"]
                            if op not in REDUCE_OPS:
                                raise ValueError(
                                    f"unsupported collective op {op!r}")
                            result = REDUCE_OPS[op](parts)
                        elif err_body is None:
                            err_body = self._dag_err_body(
                                ctx, exc.RayError(
                                    "collective peer failed this step"))
                    except ChannelClosed:
                        # A peer's ring closed mid-step (teardown or peer
                        # death): the pipeline is coming down.
                        break
                    except BaseException as e:  # noqa: BLE001
                        if err_body is None:
                            err_body = self._dag_err_body(ctx, e)
                if out is not None:
                    if err_body is not None:
                        body = err_body
                    else:
                        body, tok = device_plane.dag_encode_body(
                            ctx, _transport.OK, result,
                            local_ok=dev_local_ok, nreaders=nreaders)
                        if tok is not None:
                            live_tokens.append(tok)
                            if len(live_tokens) >= 512:
                                live_tokens = [
                                    t for t in live_tokens
                                    if device_plane.local_is_registered(t)]
                    _transport.send(store, out, body, nreaders, slot_bytes,
                                    mint)
                    occ_gauge.set(out.stats()["occupancy"], tags=occ_tags)
                rec.end("dag", "dag:step", t0, id=span_id,
                        method=method_name, wait_us=wait_us)
        finally:
            # Reclaim rung-0 registry entries consumers never drained
            # (teardown mid-pipeline): the arrays are freed with the
            # tokens; drained tokens are no-ops.
            for t in live_tokens:
                device_plane.drop_local(t)
            if out is not None:
                out.close()   # cascade EOF downstream
            if coll_out is not None:
                coll_out.close()
            for ch, _ in ins + coll_ins:
                try:
                    if ch._attached:
                        store.release(ch.channel_id)
                        ch._attached = False
                except Exception:
                    pass

    # ------------------------------------------------- streaming generators --
    # In-flight stream_item calls per generator: pipelines item delivery
    # (hides per-item RTT) while bounding worker-side buffering.  Consumer
    # backpressure composes with it: the owner parks over-budget acks,
    # which stalls this window (reference: _raylet.pyx:939 streaming
    # execution + generator_waiter.cc).
    _STREAM_WINDOW = 8

    class _StreamDropped(Exception):
        """Owner released the generator: stop producing."""

    async def _run_streaming(self, spec, fn, args, kwargs):
        """Execute a generator task, shipping each yielded value to the
        owner as its own object (see core_worker.h_stream_item).  Returns
        None — the completion object's value (reference:
        remote_function.py:404 num_returns='streaming')."""
        tid = spec["task_id"]
        loop = asyncio.get_running_loop()
        conn = await self.core._peer_owner(tuple(spec["owner_addr"]))
        progress = [0]     # item count, shared with the sync-thread driver
        try:
            import inspect
            if inspect.isasyncgenfunction(fn):
                self._running[tid] = (asyncio.current_task(), True)
                agen = fn(*args, **kwargs)
                pending: deque = deque()
                try:
                    async for val in agen:
                        fut = rpc.spawn(self._emit_stream_item(
                            conn, spec, progress[0], val))
                        progress[0] += 1
                        pending.append(fut)
                        while len(pending) >= self._STREAM_WINDOW:
                            await pending.popleft()
                    while pending:
                        await pending.popleft()
                finally:
                    # Settle stragglers so an exception above doesn't leave
                    # unretrieved task errors behind.
                    for fut in pending:
                        fut.cancel()
                    await asyncio.gather(*pending, return_exceptions=True)
                    # Close the generator NOW (async-for only closes on
                    # clean exhaustion): a dropped/cancelled stream must
                    # run the producer's cleanup (e.g. the serving
                    # engine retiring the request and freeing its KV
                    # pages) immediately, not at a later GC cycle.
                    try:
                        await agen.aclose()
                    except Exception:
                        pass
            else:
                gen = fn(*args, **kwargs)
                if not hasattr(gen, "__iter__"):
                    raise exc.RayError(
                        f"num_returns='streaming' requires a generator "
                        f"function, got {type(gen).__name__}")
                self._running[tid] = (asyncio.current_task(), False)
                await loop.run_in_executor(
                    self.core.executor,
                    lambda: self._run_sync(
                        tid, self._drive_sync_gen,
                        (gen, spec, conn, loop, progress), {}))
        except self._StreamDropped:
            # Consumer abandoned the stream mid-flight: finish quietly
            # (the completion ref still resolves to None).
            return None
        except BaseException:
            # Ordered after every delivered item on the same socket, so the
            # owner finalizes with an accurate count before the error reply
            # (which travels the push connection) resolves the completion
            # ref to the exception.
            try:
                await conn.call("stream_end", {
                    "task_id": tid, "count": progress[0], "errored": True,
                    "attempt": spec.get("retries_left", 0)})
            except (rpc.RpcError, asyncio.TimeoutError):
                pass
            raise
        await conn.call("stream_end", {
            "task_id": tid, "count": progress[0],
            "attempt": spec.get("retries_left", 0)})
        return None

    def _drive_sync_gen(self, gen, spec, conn, loop, progress):
        """Iterate a sync generator on an executor thread; each yield hands
        the value to the loop for serialization + delivery (serialization
        context is loop-confined), blocking only when the in-flight window
        fills.  Runs under _run_sync, so cancel_task's async-exc lands
        between yields exactly like it lands in a plain sync task."""
        window: deque = deque()

        def _drain_one():
            cf = window.popleft()
            err = cf.exception()   # blocks; surfaces _StreamDropped/conn loss
            if err is not None:
                raise err
        try:
            for val in gen:
                cf = asyncio.run_coroutine_threadsafe(
                    self._emit_stream_item(conn, spec, progress[0], val),
                    loop)
                progress[0] += 1
                window.append(cf)
                while len(window) >= self._STREAM_WINDOW:
                    _drain_one()
            while window:
                _drain_one()
        finally:
            gen.close()

    async def _emit_stream_item(self, conn, spec, index: int, value):
        from .streaming import item_object_id
        oid = item_object_id(spec["task_id"], index)
        entry = await self._serialize_value(oid, value,
                                            caller_addr=spec.get("owner_addr"))
        # timeout=0: the reply is the consumer's backpressure credit —
        # it legitimately parks until the consumer drains.
        reply = await conn.call("stream_item", {
            "task_id": spec["task_id"], "index": index, "entry": entry,
            "attempt": spec.get("retries_left", 0)}, timeout=0)
        if isinstance(reply, dict) and reply.get("dropped"):
            raise self._StreamDropped()

    async def _execute(self, spec):
        if _TRACE_EXEC:
            logger.warning("EXEC %s t=%.3f", spec.get("method")
                           or spec.get("name"), time.monotonic())
        loop = asyncio.get_running_loop()
        prev_task_id = self.core.current_task_id
        self.core.current_task_id = spec["task_id"]
        if spec["task_id"] in self._cancel_requested:
            # Cancelled while queued behind the task lock / actor semaphore.
            self._cancel_requested.discard(spec["task_id"])
            self.core.current_task_id = prev_task_id
            return {"status": "cancelled"}
        dl = spec.get("deadline")
        if dl and time.time() > dl + rpc.DEADLINE_SKEW_SLACK_S:
            # Budget spent before execution even started (queued behind a
            # long predecessor, or delivered late by a gray link): fail
            # typed instead of burning worker time on a result the owner
            # already wrote off.  Slack: this clock is not the owner's.
            self.core.current_task_id = prev_task_id
            return self._error_reply(
                exc.DeadlineExceededError(
                    f"deadline exceeded before execution of "
                    f"{spec.get('name') or spec.get('method', '')}"),
                "deadline exceeded before execution")
        # Registered from the very start: a cancel arriving during arg
        # resolution cancels this coroutine (user code hasn't run yet).
        self._running[spec["task_id"]] = (asyncio.current_task(), True)
        self.core.record_task_event(
            spec["task_id"], spec.get("name") or spec.get("method", ""),
            "RUNNING", job_id=spec.get("job_id") or b"")
        # Ambient deadline for the async paths (arg resolution, coroutine
        # actor methods) — the sync path re-installs it on its executor
        # thread in _run_sync.
        from . import deadlines
        _dl_tok = deadlines.set_current(dl) if dl else None
        strat = spec.get("scheduling_strategy") or {}
        prev_pg = self.core.current_placement_group
        if strat.get("type") == "placement_group":
            # Feeds util.placement_group.get_current_placement_group; the
            # finally below restores, so pooled workers don't leak a task's
            # PG context into the next task (actors keep theirs: prev_pg is
            # the actor-lifetime context set in h_actor_init).
            self.core.current_placement_group = {"pg_id": strat["pg_id"]}
        try:
            args, kwargs = await self._resolve_arg_entries(spec["args"])
            tid = spec["task_id"]
            if spec.get("actor_id"):
                if self.actor is None:
                    raise exc.RayError("actor task on non-actor worker")
                method = getattr(self.actor, spec["method"])
                if spec.get("streaming"):
                    result = await self._run_streaming(spec, method,
                                                       args, kwargs)
                elif asyncio.iscoroutinefunction(method):
                    if spec.get("trace"):
                        from ..util import tracing
                        with tracing.execution_span(self.core, spec):
                            result = await method(*args, **kwargs)
                    else:
                        result = await method(*args, **kwargs)
                else:
                    self._running[tid] = (asyncio.current_task(), False)
                    result = await loop.run_in_executor(
                        self.core.executor,
                        lambda: self._run_sync(tid, method, args, kwargs,
                                               spec))
            else:
                fn = await self._load_function(spec["fn_id"])
                if spec.get("streaming"):
                    result = await self._run_streaming(spec, fn, args, kwargs)
                else:
                    self._running[tid] = (asyncio.current_task(), False)
                    result = await loop.run_in_executor(
                        self.core.executor,
                        lambda: self._run_sync(tid, fn, args, kwargs,
                                               spec))
            returns = await self._serialize_returns(
                spec["task_id"], spec["nreturns"], result,
                caller_addr=spec.get("owner_addr"))
            reply = {"status": "ok", "returns": returns}
            caller = spec.get("owner_addr")
            if caller is not None:
                # Live borrows of caller-owned refs ride the reply so the
                # caller ledgers them BEFORE dropping its submitted arg pins
                # — an eager borrow_add notify travels a different socket
                # than this reply and can arrive after the pins are gone,
                # letting the owner free an object a stored ref still needs.
                borrows = self.core.reference_counter.borrowed_from(
                    tuple(caller))
                if borrows:
                    reply["borrows"] = borrows
                    reply["borrower_id"] = self.core.worker_id
            return reply
        except asyncio.CancelledError:
            # cancel_task cancelled an async actor method's coroutine.
            return {"status": "cancelled"}
        except exc.TaskCancelledError:
            # cancel_task raised inside the sync function's thread.
            return {"status": "cancelled"}
        except Exception as e:  # noqa: BLE001 — every user error is reported
            return self._error_reply(e)
        finally:
            if _dl_tok is not None:
                deadlines.reset(_dl_tok)
            self._running.pop(spec["task_id"], None)
            self.core.current_task_id = prev_task_id
            self.core.current_placement_group = prev_pg

    async def h_actor_init(self, conn, spec):
        strat = spec.get("scheduling_strategy") or {}
        if strat.get("type") == "placement_group":
            # PG context for the whole actor lifetime (reference:
            # get_current_placement_group inside actor methods).
            self.core.current_placement_group = {"pg_id": strat["pg_id"]}
        blob = await self.core.gcs.call(
            "kv_get", {"ns": "actor_cls", "key": spec["class_id"].hex()
                       if isinstance(spec["class_id"], bytes)
                       else spec["class_id"]})
        if blob is None:
            raise exc.RayError("actor class not exported")
        cls = get_context().loads_code(blob)
        args, kwargs = await self._resolve_arg_entries(spec["args"])
        loop = asyncio.get_running_loop()
        self.actor = await loop.run_in_executor(
            self.core.executor, lambda: cls(*args, **kwargs))
        self._sync_method_cache.clear()
        self._coro_method_cache.clear()
        self._fast_method_ok.clear()
        self.actor_id = spec["actor_id"]
        self.core.current_actor_id = spec["actor_id"]
        max_conc = spec.get("max_concurrency", 1) or 1
        import inspect as _inspect
        self._actor_is_async = any(
            asyncio.iscoroutinefunction(getattr(type(self.actor), m, None))
            or _inspect.isasyncgenfunction(getattr(type(self.actor), m,
                                                   None))
            for m in dir(type(self.actor)) if not m.startswith("__"))
        if self._actor_is_async and max_conc == 1:
            max_conc = 1000  # async actors default to high concurrency
        self._max_concurrency = max_conc
        self._actor_sem = asyncio.Semaphore(max_conc)
        self._group_sems = {
            name: asyncio.Semaphore(int(n))
            for name, n in (spec.get("concurrency_groups") or {}).items()}
        return True

    # -------------------------------------------------- live profiling ---
    # (reference: dashboard/modules/reporter/profile_manager.py — py-spy
    # CPU flamegraphs + memray dumps launched against a live worker.
    # Neither tool ships in this image, so the equivalents are built in:
    # a pure-Python stack dump and a sampling CPU profiler.)

    async def h_stacks(self, conn, p):
        """All threads' current stacks (the py-spy `dump` equivalent;
        shared implementation in diagnosis.dump_stacks, which also
        carries folded stacks for flamegraph merging)."""
        out = diagnosis.dump_stacks()
        out["actor"] = bool(self.actor_id)
        return out

    async def h_cpu_profile(self, conn, p):
        """Sampling CPU profile: poll every thread's frames at ~100Hz for
        `duration_s`, aggregate identical stacks (the py-spy `record`
        equivalent, pure Python; shared implementation in
        diagnosis.cpu_profile)."""
        return await diagnosis.cpu_profile(p.get("duration_s", 5.0),
                                           p.get("interval_s", 0.01))

    async def h_exec_stats(self, conn, p):
        """Cheap executor-activity probe for the agent's lease-stall
        detector: tasks started / currently running, as AGES (monotonic
        clocks don't compare across processes)."""
        tr = diagnosis.task_tracker()
        return tr.stats() if tr is not None else {"running": None}

    def _resolve_queued_cancel(self, task_id: bytes) -> bool:
        """Pull a still-queued task out of the chunked-drain queues and
        resolve its push future as cancelled. True if found."""
        for q in (self._task_q, self._serial_q):
            for item in q:
                if item[0]["task_id"] == task_id:
                    q.remove(item)
                    if not item[1].done():
                        item[1].set_result({"status": "cancelled"})
                    return True
        # Already popped into an executing chunk but not yet started:
        # resolve the push reply NOW (the caller must not wait for the
        # chunk's long predecessors) and leave a _cancel_requested mark so
        # the chunk's executor-thread check skips the body.  Best-effort
        # race like the reference's: a task between that check and its
        # _running registration may still run, with its reply discarded.
        for chunk in self._active_chunks:
            for spec, fut in chunk:
                if spec["task_id"] == task_id and not fut.done() \
                        and task_id not in self._running:
                    self._cancel_requested.add(task_id)
                    fut.set_result({"status": "cancelled"})
                    return True
        return False

    async def h_cancel_task(self, conn, p):
        """Cancel a task (reference: CoreWorkerService CancelTask,
        core_worker.proto:531). Async actor methods: cancel the coroutine.
        Sync functions: raise TaskCancelledError inside the executing thread
        (the serial-execution guarantee is preserved — the dispatch
        coroutine keeps awaiting until the thread actually unwinds).
        force=True exits the process and is rejected by the owner for actor
        tasks, so only dedicated lease workers die."""
        task_id = p["task_id"]
        if p.get("force"):
            if task_id in self._running:
                asyncio.get_running_loop().call_later(
                    0.05, lambda: os._exit(1))
            elif not self._resolve_queued_cancel(task_id):
                # Not dispatched yet: honor the cancel at dispatch instead
                # of letting the task run after cancel() returned True.
                self._cancel_requested.add(task_id)
            return True
        entry = self._running.get(task_id)
        if entry is None:
            # Queued behind the serial lock / pipelined behind a long
            # task: resolve its push reply NOW — the caller's get() must
            # not wait for the drain to reach it behind a 30s task
            # (reference: queued tasks cancel immediately out of the
            # scheduling queue, task_receiver.cc). Otherwise (push not
            # arrived yet) mark for cancellation at dispatch.
            if not self._resolve_queued_cancel(task_id):
                self._cancel_requested.add(task_id)
            return True
        task, is_async = entry
        if is_async:
            # Covers async actor methods AND any task still resolving args
            # (user code hasn't started; cancelling the coroutine is safe).
            if not p.get("interrupt_running", True):
                # Deadline chase: an executing ASYNC method is as
                # vulnerable to half-mutated actor state as a sync one
                # (the cancel lands at any await between mutations) —
                # let it finish; the reply is discarded owner-side.
                return True
            task.cancel()
            return True
        with self._thread_guard:
            tid = self._running_threads.get(task_id)
            if tid is not None:
                if not p.get("interrupt_running", True):
                    # Deadline chase on a sync actor method: the owner
                    # already resolved the returns, and interrupting the
                    # thread mid-method could leave actor state half-
                    # mutated — let it finish; the reply is discarded
                    # owner-side (_deadline_expired).
                    return True
                import ctypes
                self._cancel_intent.add(task_id)
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid),
                    ctypes.py_object(exc.TaskCancelledError))
                return True
        if task is None:
            # Chunk-executed item between registration and thread start:
            # mark for the pre-run check inside the chunk runner.
            self._cancel_requested.add(task_id)
            return True
        # Sync task dispatched to the executor but its thread hasn't begun:
        # cancelling the awaiting coroutine cancels the not-yet-started
        # pool callable too.
        task.cancel()
        return True

    async def h_kill(self, conn, p):
        logger.info("worker exiting on kill request")
        asyncio.get_running_loop().call_later(0.05, lambda: os._exit(0))
        return True


async def amain():
    rpc.enable_eager_tasks()
    worker_id = bytes.fromhex(os.environ["RAY_TPU_WORKER_ID"])
    agent_addr = json.loads(os.environ["RAY_TPU_AGENT_ADDR"])
    gcs_addr = json.loads(os.environ["RAY_TPU_GCS_ADDR"])
    node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
    store_path = os.environ["RAY_TPU_STORE_PATH"]
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]

    core = CoreWorker(
        mode="worker", gcs_address=gcs_addr, agent_address=agent_addr,
        store_path=store_path, node_id=node_id, session_dir=session_dir,
        worker_id=worker_id, job_id=b"\x00\x00\x00\x00")
    await core.start_in_loop()
    executor = Executor(core, None)
    exec_handlers = {
        "push_task": executor.h_push_task,
        "push_actor_task": executor.h_push_actor_task,
        "submit_batch": executor.h_submit_batch,
        "actor_init": executor.h_actor_init,
        "cancel_task": executor.h_cancel_task,
        "kill": executor.h_kill,
        "stacks": executor.h_stacks,
        "cpu_profile": executor.h_cpu_profile,
        "exec_stats": executor.h_exec_stats,
    }
    core._server.handlers.update(exec_handlers)
    fast_handlers = {
        "push_task": executor.f_push_task,
        "push_actor_task": executor.f_push_actor_task,
        "submit_batch": executor.f_submit_batch,
    }
    core._server.fast_handlers = fast_handlers
    for c in core._server.connections:
        c.fast_handlers = fast_handlers
    # Register with the agent over a dedicated connection that stays open —
    # the agent uses its closure to detect worker death, and sends actor_init
    # over it, so it must carry the executor handlers too.
    agent_conn = await rpc.connect(tuple(agent_addr), name="worker->agent",
                                   handlers=exec_handlers)
    reply = await agent_conn.call("register_worker", {
        "worker_id": worker_id, "address": list(core.address)})

    # Make this process's runtime available to user code (nested submits).
    from . import worker as worker_mod
    worker_mod._set_global_from_existing(core)

    import ray_tpu
    ray_tpu._set_runtime_for_worker(core)

    cfg = get_config()
    if cfg.diagnosis_enabled:
        tracker = diagnosis.init_task_tracker(
            multiple=cfg.diagnosis_task_hang_multiple,
            min_s=cfg.diagnosis_task_hang_min_s,
            default_s=cfg.diagnosis_task_hang_default_s,
            thread_lookup=lambda tid: executor._running_threads.get(tid))
        core._diag_tracker = tracker

        def _forward_anomaly(info):
            # Watchdog thread -> loop thread -> best-effort GCS notify
            # (triggers the black-box capture; counter+recorder already
            # recorded process-locally by record_anomaly).
            def _send():
                try:
                    if core.gcs is not None and not core.gcs.closed:
                        core.gcs.notify("report_anomaly", info)
                except Exception:
                    pass
            try:
                core.loop.call_soon_threadsafe(_send)
            except RuntimeError:
                pass

        watchdog = diagnosis.Watchdog(
            daemon_name="worker", node_id=node_id.hex(),
            detectors=[tracker.detector()], notify=_forward_anomaly,
            poll_s=cfg.diagnosis_poll_ms / 1000.0)
        watchdog.start()

    # Die with the agent (reference: a core worker exits when its raylet
    # IPC socket closes — node death must take its workers down, or dead
    # nodes keep computing and failure handling never engages).
    async def _agent_watch():
        while not agent_conn.closed:
            await asyncio.sleep(0.5)
        logging.getLogger("ray_tpu").warning(
            "agent connection lost; worker exiting")
        os._exit(1)

    rpc.spawn(_agent_watch())    # strong ref: bare tasks can be GC'd

    await asyncio.Event().wait()


def main():
    logging.basicConfig(level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"))
    signal.signal(signal.SIGTERM, lambda *a: os._exit(0))
    from .node import install_daemon_profiler
    install_daemon_profiler("worker")
    from .auth import require_process_token
    require_process_token("worker")
    try:
        asyncio.run(amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
